"""Pipeline instruction schedules.

Analog of the reference's schedule ISA (`runtime/pipe/schedule.py`:
``TrainSchedule``:182, ``InferenceSchedule``:129, instruction classes
:317-477). Schedules are pure-Python generators of per-step instruction
lists, unit-testable without any devices (the property the reference proves
with `tests/unit/test_pipe_schedule.py`).

On TPU the compiled pipeline (`runtime/pipe/engine.py`) executes a
collective-permute schedule fused into one XLA program, so these instruction
streams are not dispatched one-by-one at runtime; they remain the canonical
*specification* of the pipeline order — used for schedule introspection,
debugging, and as the contract the compiled rotation implements — and the
generator design (1F1B with warmup/steady/cooldown phases) matches what the
compiled program does.

Own design, not a translation: the reference derives (micro_batch, phase)
from clock-cycle parity arithmetic; here the schedule is produced by an
explicit event simulation of the 1F1B policy, which makes the correctness
invariants (send-before-recv, forward-before-backward, buffer bounds)
direct consequences of the simulation.
"""

from functools import lru_cache as _functools_lru_cache
from typing import List


class PipeSchedule:
    """Base class: yields lists of :class:`PipeInstruction` per step.

    Args mirror the reference (`schedule.py:33`): ``micro_batches`` (per
    train-batch micro-batches), ``stages`` (pipeline depth), ``stage_id``
    (which stage this schedule drives).
    """

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert stages > 0 and micro_batches > 0
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    def steps(self):
        raise NotImplementedError()

    def num_pipe_buffers(self):
        """Upper bound on concurrently-live activation buffers."""
        return self.micro_batches

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only wavefront: microbatch ``m`` runs on stage ``s`` at round
    ``m + s`` (reference `schedule.py:129`)."""

    def num_pipe_buffers(self):
        return 2  # double buffer: recv next while computing current

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for round_id in range(total):
            cmds: List[PipeInstruction] = []
            m = round_id - self.stage_id
            if self._valid_micro_batch(m):
                buf = m % self.num_pipe_buffers()
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(buf, stage_id=self.stage_id,
                                               micro_batch_id=m))
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buf, stage_id=self.stage_id,
                                               micro_batch_id=m))
                cmds.append(ForwardPass(buf, stage_id=self.stage_id,
                                        micro_batch_id=m))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf, stage_id=self.stage_id,
                                               micro_batch_id=m))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B: warmup forwards, steady alternation, cooldown backwards, then
    gradient reduction and the optimizer step (reference `schedule.py:182`).
    """

    @staticmethod
    def buffers_for(micro_batches, stages, stage_id):
        """In-flight activations at stage s are bounded by the 1F1B depth
        remaining to the last stage (reference `schedule.py:243-247`)."""
        if micro_batches <= stages - stage_id:
            return micro_batches
        return stages - stage_id + 1

    def num_pipe_buffers(self):
        return self.buffers_for(self.micro_batches, self.stages,
                                self.stage_id)

    def _warmup(self, stage_id):
        """Forwards issued before the first backward under 1F1B."""
        return min(self.micro_batches, self.stages - stage_id)

    def _simulate(self):
        """Round-based event simulation of all stages; returns
        per-stage, per-round instruction lists. The simulation is
        stage-independent, so it's computed once per (M, S)."""
        return _simulate_rounds(self.micro_batches, self.stages)

    def steps(self):
        for round_cmds in self._simulate():
            yield list(round_cmds[self.stage_id])
        # epilogue: tied-weight reduction, DP gradient reduction, step
        yield [ReduceTiedGrads(stage_id=self.stage_id),
               ReduceGrads(stage_id=self.stage_id),
               OptimizerStep(stage_id=self.stage_id)]


@_functools_lru_cache(maxsize=128)
def _simulate_rounds(M, S):
    """Round-based event simulation of all S stages for M microbatches."""
    def warmup(s):
        return min(M, S - s)

    # Activations/gradients that have *arrived* and await consumption.
    acts_in = [list(range(M)) if s == 0 else [] for s in range(S)]
    grads_in = [[] for _ in range(S)]
    fwds_done = [0] * S
    bwds_done = [0] * S
    rounds = []  # rounds[r][s] -> [instructions]
    while any(b < M for b in bwds_done):
        round_cmds = [[] for _ in range(S)]
        # arrivals produced this round, delivered for the *next* round
        act_arrivals = []   # (stage, micro_batch)
        grad_arrivals = []
        for s in range(S):
            cmds = round_cmds[s]
            # 1F1B in-flight bound: at most warmup(s) forwards may be
            # outstanding (forwarded but not yet backwarded) — this is
            # what caps activation memory at the pipeline depth.
            in_flight = fwds_done[s] - bwds_done[s]
            fwd_ready = (bool(acts_in[s]) and fwds_done[s] < M
                         and in_flight < warmup(s))
            bwd_ready = bool(grads_in[s])
            # Once warmup forwards are in flight, prefer backward
            # whenever one is ready.
            do_bwd = bwd_ready and (fwds_done[s] >= warmup(s)
                                    or not fwd_ready)
            if do_bwd:
                m = grads_in[s].pop(0)
                buf = m % TrainSchedule.buffers_for(M, S, s)
                if s != S - 1:
                    cmds.append(RecvGrad(buf, stage_id=s,
                                         micro_batch_id=m))
                cmds.append(BackwardPass(buf, stage_id=s,
                                         micro_batch_id=m))
                if s != 0:
                    cmds.append(SendGrad(buf, stage_id=s,
                                         micro_batch_id=m))
                    grad_arrivals.append((s - 1, m))
                bwds_done[s] += 1
            elif fwd_ready:
                m = acts_in[s].pop(0)
                buf = m % TrainSchedule.buffers_for(M, S, s)
                if s == 0 or s == S - 1:
                    cmds.append(LoadMicroBatch(buf, stage_id=s,
                                               micro_batch_id=m))
                if s != 0:
                    cmds.append(RecvActivation(buf, stage_id=s,
                                               micro_batch_id=m))
                cmds.append(ForwardPass(buf, stage_id=s,
                                        micro_batch_id=m))
                if s != S - 1:
                    cmds.append(SendActivation(buf, stage_id=s,
                                               micro_batch_id=m))
                    act_arrivals.append((s + 1, m))
                else:
                    # Loss is local to the last stage: its backward is
                    # ready the round after its forward.
                    grad_arrivals.append((s, m))
                fwds_done[s] += 1
            # else: bubble
        for s, m in act_arrivals:
            acts_in[s].append(m)
        for s, m in grad_arrivals:
            grads_in[s].append(m)
        rounds.append(round_cmds)
    return rounds


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule: plain grad-accumulated DP training
    (reference `schedule.py:281`)."""

    def num_pipe_buffers(self):
        return 1

    def steps(self):
        for m in range(self.micro_batches):
            cmds = [LoadMicroBatch(0, stage_id=0, micro_batch_id=m),
                    ForwardPass(0, stage_id=0, micro_batch_id=m),
                    BackwardPass(0, stage_id=0, micro_batch_id=m)]
            if m == self.micro_batches - 1:
                cmds.extend([ReduceGrads(stage_id=0),
                             OptimizerStep(stage_id=0)])
            yield cmds


# ---------------------------------------------------------------------------
# Instruction ISA (reference `schedule.py:317-477`)
# ---------------------------------------------------------------------------
class PipeInstruction:
    """A step in the pipeline program; carries arbitrary kwargs
    (``stage_id``, ``micro_batch_id``...)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (type(self) is type(other)) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((type(self), tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Apply the optimizer at the end of the train batch."""


class ReduceGrads(PipeInstruction):
    """Reduce accumulated gradients over the data-parallel axis."""


class ReduceTiedGrads(PipeInstruction):
    """Reduce gradients of tied modules over the stages that share them."""


class BufferOpInstruction(PipeInstruction):
    """Instruction operating on a pipeline buffer slot."""

    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    """Load micro-batch ``micro_batch_id`` into ``buffer_id`` (first stage
    loads inputs, last stage loads labels)."""


class ForwardPass(BufferOpInstruction):
    """Run the stage forward on ``buffer_id``."""


class BackwardPass(BufferOpInstruction):
    """Run the stage backward for ``buffer_id``."""


class SendActivation(BufferOpInstruction):
    """Send ``buffer_id`` activations to the next stage."""


class RecvActivation(BufferOpInstruction):
    """Receive activations for ``buffer_id`` from the previous stage."""


class SendGrad(BufferOpInstruction):
    """Send input-activation gradients for ``buffer_id`` upstream."""


class RecvGrad(BufferOpInstruction):
    """Receive output-activation gradients for ``buffer_id`` downstream."""
