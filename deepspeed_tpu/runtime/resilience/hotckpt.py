"""In-memory hot-checkpoint tier: seconds-scale recovery for benign
restarts.

Disk checkpoints (`checkpoint.py`) are the durable tier, but every
recovery through them replays up to ``save_interval_steps`` of work and
pays a full orbax round trip. Large-scale training systems (MegaScale;
the Gemini in-RAM checkpoint design) keep a second, much cheaper tier:
frequent device→host snapshots held in RAM, so the common benign
failures — a guard-trip rollback, a single-worker restart — resume from
the last *step or two*, not the last disk save.

:class:`HotCheckpointStore` holds up to ``capacity`` recent snapshots:

- **Snapshot isolation**: :meth:`snapshot` copies every leaf to host
  numpy with ``np.array(..., copy=True)`` (same discipline as the async
  disk path — the engine's compiled steps donate their buffers, and
  already-host leaves would otherwise alias live memory).
- **CRC stamping**: each snapshot is crc32-stamped per leaf on a
  background worker; :meth:`restore` re-verifies before handing the
  tree back, so a corrupted snapshot raises
  :class:`HotCheckpointCorruptError` instead of resuming from garbage
  (the restore ladder then falls through to disk).
- **Mirror**: with ``mirror_dir`` each snapshot is also staged to a
  local directory (``hot-<tag>/state.npz`` + ``hot.json``, tmp+rename
  atomic) — in RAM the tier dies with the process, the mirror is what
  lets a *restarted* process still skip the disk round trip. Point it
  at fast local disk (or a peer's export) rather than the shared
  checkpoint filesystem.

The store knows nothing about the engine: it moves opaque pytrees. The
engine's restore ladder (``_auto_resume``) decides hot RAM → hot mirror
→ disk and re-places leaves on the current mesh.
"""

import collections
import json
import logging
import os
import shutil
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import jax

from deepspeed_tpu.runtime.resilience.checkpoint import _leaf_checksums

logger = logging.getLogger(__name__)

MIRROR_PREFIX = "hot-"
MIRROR_TMP_PREFIX = ".tmp.hot-"
MIRROR_STATE_NAME = "state.npz"
MIRROR_META_NAME = "hot.json"
MIRROR_LATEST_NAME = "hot-latest"


class HotCheckpointCorruptError(RuntimeError):
    """A hot snapshot (RAM or mirror) failed CRC/structure validation."""

    def __init__(self, what, reason):
        super().__init__(f"corrupt hot checkpoint ({what}): {reason}")
        self.what = what
        self.reason = reason


class HotSnapshot:
    """One host-RAM snapshot: tag + state pytree + meta + fingerprint."""

    __slots__ = ("tag", "state", "meta", "topology", "checksums", "t")

    def __init__(self, tag, state, meta, topology):
        self.tag = str(tag)
        self.state = state
        self.meta = meta
        self.topology = topology
        self.checksums = None   # stamped by the background worker
        self.t = time.time()


def _snapshot_to_host(state):
    return jax.tree_util.tree_map(
        lambda x: np.array(jax.device_get(x), copy=True), state)


class HotCheckpointStore:
    def __init__(self, capacity=1, mirror_dir=None, mirror_keep=1,
                 process_index=0):
        self.capacity = max(1, int(capacity))
        self.mirror_dir = os.path.abspath(mirror_dir) if mirror_dir \
            else None
        self.mirror_keep = max(1, int(mirror_keep))
        self.process_index = int(process_index)
        self._snaps = collections.deque(maxlen=self.capacity)
        self._pool = None
        self._pending = None

    # ------------------------------------------------------------------
    # snapshot
    # ------------------------------------------------------------------
    def snapshot(self, tag, state, meta, topology=None):
        """Copy ``state`` to host RAM and keep it; CRC stamping and the
        optional mirror write happen on a background worker (call
        :meth:`wait` — :meth:`restore` does — before relying on them)."""
        self.wait()   # surface a previous stamping/mirror failure
        snap = HotSnapshot(tag, _snapshot_to_host(state), meta, topology)
        self._snaps.append(snap)
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="hot_ckpt")
        self._pending = self._pool.submit(self._stamp_and_mirror, snap)
        return snap

    def _stamp_and_mirror(self, snap):
        snap.checksums = _leaf_checksums(snap.state)
        if self.mirror_dir:
            self._write_mirror(snap)

    def wait(self):
        """Join the in-flight stamp/mirror job, raising its error."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def latest(self):
        """Newest snapshot (CRC stamped — joins the background job), or
        None when the store is empty."""
        if not self._snaps:
            return None
        self.wait()
        return self._snaps[-1]

    # ------------------------------------------------------------------
    # restore (RAM tier)
    # ------------------------------------------------------------------
    def restore(self, snap=None):
        """``(state, meta, topology)`` from the newest (or the given)
        snapshot after CRC verification. Raises
        :class:`HotCheckpointCorruptError` on a mismatch — callers fall
        through to the next ladder tier."""
        if snap is None:
            snap = self.latest()
        else:
            self.wait()
        if snap is None:
            return None
        if snap.checksums is None:
            raise HotCheckpointCorruptError(
                f"ram:{snap.tag}", "snapshot was never CRC-stamped")
        actual = _leaf_checksums(snap.state)
        if actual != snap.checksums:
            bad = sorted(k for k in snap.checksums
                         if actual.get(k) != snap.checksums[k])
            raise HotCheckpointCorruptError(
                f"ram:{snap.tag}",
                f"crc mismatch on {len(bad)} leaves (first: {bad[:3]})")
        return snap.state, snap.meta, snap.topology

    # ------------------------------------------------------------------
    # mirror tier
    # ------------------------------------------------------------------
    def _write_mirror(self, snap):
        final = os.path.join(self.mirror_dir, MIRROR_PREFIX + snap.tag)
        tmp = os.path.join(self.mirror_dir, MIRROR_TMP_PREFIX + snap.tag)
        try:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp, exist_ok=True)
            leaves, _ = jax.tree_util.tree_flatten_with_path(snap.state)
            arrays = {jax.tree_util.keystr(path): np.asarray(leaf)
                      for path, leaf in leaves}
            with open(os.path.join(tmp, MIRROR_STATE_NAME), "wb") as f:
                np.savez(f, **arrays)
            with open(os.path.join(tmp, MIRROR_META_NAME), "w") as f:
                json.dump({"tag": snap.tag, "t": snap.t,
                           "process_index": self.process_index,
                           "meta": snap.meta, "topology": snap.topology,
                           "checksums": snap.checksums}, f)
            if os.path.isdir(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
            latest_tmp = os.path.join(self.mirror_dir,
                                      MIRROR_LATEST_NAME + ".tmp")
            with open(latest_tmp, "w") as f:
                f.write(snap.tag)
            os.replace(latest_tmp,
                       os.path.join(self.mirror_dir, MIRROR_LATEST_NAME))
            self._gc_mirror()
        except OSError as e:
            # The mirror is an accelerator, not the durable tier — a
            # failed write degrades recovery latency, never correctness.
            logger.warning("hot-checkpoint mirror write failed: %s", e)
            shutil.rmtree(tmp, ignore_errors=True)

    def _gc_mirror(self):
        entries = []
        for name in os.listdir(self.mirror_dir):
            path = os.path.join(self.mirror_dir, name)
            if name.startswith(MIRROR_PREFIX) and os.path.isdir(path):
                entries.append((os.path.getmtime(path), path))
            elif name.startswith(MIRROR_TMP_PREFIX):
                shutil.rmtree(path, ignore_errors=True)
        entries.sort(reverse=True)
        for _, path in entries[self.mirror_keep:]:
            shutil.rmtree(path, ignore_errors=True)

    @staticmethod
    def load_mirror(mirror_dir, template):
        """``(state, meta, topology)`` from the newest mirror snapshot
        under ``mirror_dir``, rebuilt against ``template``'s pytree
        structure (mirrors store leaves by key path — the restoring
        process supplies the structure, typically its freshly
        initialized state tree). Returns None when the dir holds no
        usable mirror; raises :class:`HotCheckpointCorruptError` on a
        CRC/structure mismatch."""
        mirror_dir = os.path.abspath(mirror_dir)
        latest = os.path.join(mirror_dir, MIRROR_LATEST_NAME)
        candidates = []
        try:
            with open(latest) as f:
                tag = f.read().strip()
            if tag:
                candidates.append(
                    os.path.join(mirror_dir, MIRROR_PREFIX + tag))
        except OSError:
            pass
        try:
            extra = [os.path.join(mirror_dir, n)
                     for n in os.listdir(mirror_dir)
                     if n.startswith(MIRROR_PREFIX)
                     and os.path.isdir(os.path.join(mirror_dir, n))]
            extra.sort(key=os.path.getmtime, reverse=True)
            candidates.extend(p for p in extra if p not in candidates)
        except OSError:
            return None
        for path in candidates:
            try:
                return HotCheckpointStore._load_one_mirror(path, template)
            except Exception as e:
                # a torn mirror can fail anywhere in the decode stack
                # (zipfile, npy header, json, CRC) — skip to the next
                logger.warning("skipping unusable hot mirror %s: %s",
                               path, e)
        return None

    @staticmethod
    def _load_one_mirror(path, template):
        with open(os.path.join(path, MIRROR_META_NAME)) as f:
            doc = json.load(f)
        leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
        with np.load(os.path.join(path, MIRROR_STATE_NAME)) as npz:
            restored = []
            for key_path, _ in leaves:
                key = jax.tree_util.keystr(key_path)
                if key not in npz:
                    raise HotCheckpointCorruptError(
                        path, f"mirror missing leaf {key} — snapshot is "
                        "from a different state tree")
                restored.append(np.array(npz[key]))
        state = jax.tree_util.tree_unflatten(treedef, restored)
        checksums = doc.get("checksums")
        if checksums:
            actual = _leaf_checksums(state)
            for key, rec in checksums.items():
                got = actual.get(key)
                if got is None or got["crc32"] != rec["crc32"]:
                    raise HotCheckpointCorruptError(
                        path, f"crc mismatch for leaf {key}")
        return state, doc.get("meta"), doc.get("topology")

    def close(self):
        try:
            self.wait()
        finally:
            if self._pool is not None:
                self._pool.shutdown(wait=True)
                self._pool = None
            self._snaps.clear()
