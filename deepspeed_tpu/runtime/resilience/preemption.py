"""Graceful preemption: catch SIGTERM, checkpoint at the next step
boundary, exit cleanly.

TPU pod slices (and any spot/preemptible capacity) announce eviction
with SIGTERM and a grace window. The handler only sets a flag — all
actual work (save + raise) happens synchronously in ``train_batch`` at
the next step boundary, where the engine state is consistent
(signal-handler-safe: no I/O, no locks in the handler itself).
"""

import faulthandler
import logging
import signal
import threading

logger = logging.getLogger(__name__)


class PreemptedError(SystemExit):
    """Raised at a step boundary after SIGTERM once the engine has saved
    a preemption checkpoint. Subclasses SystemExit so an unhandled
    preemption exits cleanly (code 0 — the work was safely persisted)
    instead of dumping a traceback; supervisors that want to keep the
    process alive can still catch it explicitly."""

    def __init__(self, message, checkpoint_path=None):
        super().__init__(0)
        self.message = message
        self.checkpoint_path = checkpoint_path

    def __str__(self):
        return self.message


class PreemptionHandler:
    """Flag-based SIGTERM latch checked between steps.

    ``install()`` chains any pre-existing SIGTERM handler (it is invoked
    after the flag is set) and is idempotent. The handler is installed
    only on the main thread — Python restricts ``signal.signal`` to it —
    and on other threads :meth:`install` degrades to flag-only mode,
    where :meth:`trigger` (used by the fault-injection harness) is the
    only way the flag gets set.
    """

    def __init__(self, signum=signal.SIGTERM):
        self.signum = signum
        self._flag = threading.Event()
        self._prev_handler = None
        self._installed = False

    def install(self):
        if self._installed:
            return self
        if threading.current_thread() is threading.main_thread():
            self._prev_handler = signal.signal(self.signum, self._on_signal)
            self._installed = True
            self._register_sigquit_dump()
        else:
            logger.warning(
                "PreemptionHandler.install() called off the main thread; "
                "SIGTERM will not be caught (flag-only mode)")
        return self

    def _register_sigquit_dump(self):
        """Register a faulthandler all-thread stack dump on SIGQUIT, so
        any resilience-enabled run answers ``kill -QUIT <pid>`` with
        "where is every thread stuck" on stderr — no config needed.
        ``chain=False``: with no prior Python handler the previous
        disposition is SIG_DFL, and chaining would re-raise into it
        (terminate + core) — replacing keeps the process running. The
        flight recorder's own SIGQUIT handler, installed later,
        supersedes this and prints the same stacks itself."""
        sigquit = getattr(signal, "SIGQUIT", None)
        if sigquit is None:       # pragma: no cover - non-POSIX
            return
        try:
            faulthandler.register(sigquit, chain=False)
            self._sigquit_registered = True
        except (AttributeError, ValueError, OSError):  # pragma: no cover
            self._sigquit_registered = False

    def uninstall(self):
        if self._installed:
            signal.signal(self.signum, self._prev_handler or signal.SIG_DFL)
            self._installed = False
            self._prev_handler = None
            if getattr(self, "_sigquit_registered", False):
                try:
                    faulthandler.unregister(signal.SIGQUIT)
                except (AttributeError, ValueError):  # pragma: no cover
                    pass
                self._sigquit_registered = False

    def _on_signal(self, signum, frame):
        self._flag.set()
        logger.warning("received signal %d: will checkpoint and exit at "
                       "the next step boundary", signum)
        if callable(self._prev_handler):
            self._prev_handler(signum, frame)

    def trigger(self):
        """Set the preemption flag directly (fault-injection path)."""
        self._flag.set()

    @property
    def preempted(self):
        return self._flag.is_set()

    def clear(self):
        self._flag.clear()
