"""Step health guards: NaN/Inf gradients, loss spikes, loss-scale collapse.

Detection is split between device and host to keep the hot path jitted:

- **NaN/Inf gradients** are detected *inside* the compiled step — when
  the guard is enabled the engine forces the gradient overflow check on
  (even for fp32/bf16 runs, where it is normally compiled out) and, for
  the ``skip_step`` action, the existing overflow-skip machinery drops
  the update without a host round-trip.
- **Loss spike** and **scale collapse** are host-side: they need
  history across steps (a rolling loss median; consecutive
  steps-at-min-scale), which the per-step metrics already carry.

:class:`StepHealthMonitor.observe` consumes one step's health signals
and returns the list of :class:`GuardTrip`\\ s; the *engine* executes
the configured action (``warn`` logs, ``skip_step`` is device-side,
``rollback_to_checkpoint`` reloads the newest valid checkpoint,
``abort`` raises :class:`HealthGuardAbort`). Trip counters are surfaced
in the engine's per-step metrics dict.
"""

import logging
import math
from collections import deque
from dataclasses import dataclass

logger = logging.getLogger(__name__)

ACTION_WARN = "warn"
ACTION_SKIP_STEP = "skip_step"
ACTION_ROLLBACK = "rollback_to_checkpoint"
ACTION_ABORT = "abort"
VALID_ACTIONS = (ACTION_WARN, ACTION_SKIP_STEP, ACTION_ROLLBACK,
                 ACTION_ABORT)

GUARD_NAN = "nan_grads"
GUARD_LOSS_SPIKE = "loss_spike"
GUARD_SCALE_COLLAPSE = "scale_collapse"


class HealthGuardAbort(RuntimeError):
    """A health guard with action=abort tripped; training must stop.

    Carries the :class:`GuardTrip` so supervisors can log/alert on the
    specific guard and step.
    """

    def __init__(self, trip):
        super().__init__(f"health guard '{trip.guard}' aborted training at "
                         f"step {trip.step}: {trip.reason}")
        self.trip = trip


@dataclass(frozen=True)
class GuardTrip:
    guard: str      # GUARD_* name
    action: str     # ACTION_* the engine must take
    step: int       # engine global step the trip fired on
    reason: str     # human-readable diagnosis

    def as_event(self):
        """Flat payload for the telemetry ``health_guard`` event."""
        return {"guard": self.guard, "action": self.action,
                "step": self.step, "reason": self.reason}


class StepHealthMonitor:
    """Host-side health state machine fed once per optimizer step.

    ``nan_action`` / ``spike_action`` / ``collapse_action`` are ACTION_*
    strings or None (guard disabled). ``fp16_dynamic`` tells the NaN
    guard that gradient overflow is *expected* dynamics (the loss scaler
    handles it), so only a non-finite loss counts as a NaN trip there.
    """

    def __init__(self, nan_action=None, spike_action=None,
                 collapse_action=None, fp16_dynamic=False,
                 spike_window=20, spike_factor=10.0, spike_min_history=5,
                 collapse_patience=10, min_scale=1.0):
        self.nan_action = nan_action
        self.spike_action = spike_action
        self.collapse_action = collapse_action
        self.fp16_dynamic = fp16_dynamic
        self.spike_window = int(spike_window)
        self.spike_factor = float(spike_factor)
        self.spike_min_history = int(spike_min_history)
        self.collapse_patience = int(collapse_patience)
        self.min_scale = float(min_scale)

        self._loss_history = deque(maxlen=self.spike_window)
        self._steps_at_min_scale = 0
        self.trip_counts = {GUARD_NAN: 0, GUARD_LOSS_SPIKE: 0,
                            GUARD_SCALE_COLLAPSE: 0}

    @property
    def enabled(self):
        return any(a is not None for a in (self.nan_action,
                                           self.spike_action,
                                           self.collapse_action))

    def reset_history(self):
        """Called by the engine after a rollback: pre-rollback history
        would re-trip against post-rollback losses."""
        self._loss_history.clear()
        self._steps_at_min_scale = 0

    def observe(self, step, loss, grad_nonfinite, cur_scale):
        """Feed one step's health signals; returns [GuardTrip, ...].

        ``loss`` is the host float loss, ``grad_nonfinite`` the in-jit
        overflow/NaN detector's verdict, ``cur_scale`` the loss scale
        after this step's update (None for non-fp16 runs).
        """
        trips = []
        step = int(step)
        loss = float(loss)
        loss_bad = not math.isfinite(loss)

        if self.nan_action is not None:
            nonfinite = bool(grad_nonfinite) and not self.fp16_dynamic
            if nonfinite or loss_bad:
                what = "loss" if loss_bad else "gradients"
                trips.append(GuardTrip(
                    GUARD_NAN, self.nan_action, step,
                    f"non-finite {what} detected (loss={loss})"))
                self.trip_counts[GUARD_NAN] += 1

        if self.spike_action is not None and not loss_bad:
            if len(self._loss_history) >= self.spike_min_history:
                baseline = sorted(self._loss_history)[
                    len(self._loss_history) // 2]
                threshold = self.spike_factor * abs(baseline)
                if threshold > 0 and abs(loss) > threshold:
                    trips.append(GuardTrip(
                        GUARD_LOSS_SPIKE, self.spike_action, step,
                        f"loss {loss:.6g} exceeds {self.spike_factor}x the "
                        f"rolling median {baseline:.6g}"))
                    self.trip_counts[GUARD_LOSS_SPIKE] += 1
            self._loss_history.append(loss)

        if self.collapse_action is not None and cur_scale is not None:
            if float(cur_scale) <= self.min_scale:
                self._steps_at_min_scale += 1
            else:
                self._steps_at_min_scale = 0
            if self._steps_at_min_scale >= self.collapse_patience:
                trips.append(GuardTrip(
                    GUARD_SCALE_COLLAPSE, self.collapse_action, step,
                    f"loss scale pinned at min ({self.min_scale}) for "
                    f"{self._steps_at_min_scale} consecutive steps — every "
                    "step is overflowing"))
                self.trip_counts[GUARD_SCALE_COLLAPSE] += 1
                self._steps_at_min_scale = 0  # one trip per episode

        for t in trips:
            logger.warning("health guard trip: %s at step %d (action=%s): %s",
                           t.guard, t.step, t.action, t.reason)
        return trips

    def metrics(self):
        """Trip counters for the engine's per-step metrics dict."""
        return {
            "health/nan_trips": self.trip_counts[GUARD_NAN],
            "health/loss_spike_trips": self.trip_counts[GUARD_LOSS_SPIKE],
            "health/scale_collapse_trips":
                self.trip_counts[GUARD_SCALE_COLLAPSE],
        }
