"""Preemption-safe checkpoint I/O: atomic writes, integrity manifests,
retention GC, validated discovery, and bounded-retry I/O.

The engine's original ``save_checkpoint`` wrote orbax state, meta.json
and the ``latest`` pointer straight into the final directory — a
preemption mid-write left a partial directory that the next run's
``load_checkpoint`` would trip over with an opaque orbax traceback.
:class:`CheckpointManager` owns all checkpoint path/IO policy instead:

- **Atomic save**: everything (orbax state, ``meta.json``,
  ``manifest.json``) is written into ``<save_dir>/.tmp.<tag>`` and the
  directory is published with a single ``os.rename``. A kill at any
  point leaves either the complete previous layout or an ignorable tmp
  dir — never a partial final checkpoint. The ``latest`` pointer is
  updated via write-to-tmp + ``os.replace``.
- **Integrity manifest**: ``manifest.json`` records a file inventory
  (relative path -> byte size) and, on single-process runs, a per-array
  crc32 checksum for every state leaf.
- **Validation + fallback**: :meth:`resolve_tag` returns the newest
  checkpoint that passes cheap validation (manifest present, inventory
  sizes match), scanning past a corrupt/partial newest one.
  :meth:`load` verifies restored leaves against the manifest checksums
  and wraps any orbax/IO failure in a typed
  :class:`CheckpointCorruptError`.
- **Retention GC**: ``keep_last_n`` prunes the oldest complete
  checkpoints after each successful save.
- **Retry**: every I/O phase runs under
  :func:`~deepspeed_tpu.runtime.resilience.retry.retry_with_backoff`.
- **Async save**: with ``async_save`` the state tree is copied to host
  synchronously (the engine's compiled steps donate their buffers) and
  the write is backgrounded on a single worker; :meth:`wait` (also
  called at the start of the next save) surfaces any failure.

The engine still owns what goes *into* a checkpoint (state/meta trees)
and how restored arrays are re-placed on the current mesh.
"""

import json
import logging
import os
import shutil
import time
import zlib
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import jax

from deepspeed_tpu.runtime.resilience import fault_injection
from deepspeed_tpu.runtime.resilience.retry import (
    RetryExhaustedError,
    retry_with_backoff,
)

logger = logging.getLogger(__name__)

STATE_SUBDIR = "state"
META_NAME = "meta.json"
MANIFEST_NAME = "manifest.json"
LATEST_NAME = "latest"
TMP_PREFIX = ".tmp."
# v2 adds the self-describing sections the elasticity subsystem reads
# (`runtime/elastic/`): "topology" (mesh shape, process count, ZeRO
# stage, offload flag) and "arrays" (per-leaf logical shape + dtype +
# PartitionSpec). v1 checkpoints stay loadable — readers treat the
# sections as optional.
MANIFEST_VERSION = 2
# Tmp dirs younger than this survive retention GC: on a shared
# filesystem a ``.tmp.<tag>`` dir that is not ours may be another
# process's *in-flight* async save, and deleting it from under that
# writer corrupts the checkpoint it is about to publish. A crashed
# attempt's leftover goes quiet, ages past the grace window, and is
# collected on a later save.
TMP_GC_GRACE_S = 900.0


def _newest_mtime(root):
    """Newest mtime anywhere under ``root`` (the dir itself, nested dirs,
    files). A writer touching any file keeps the whole tree "recent" —
    the top-level dir mtime alone misses writes inside orbax's nested
    state/ layout."""
    newest = 0.0
    try:
        newest = os.path.getmtime(root)
        for dirpath, _, filenames in os.walk(root):
            newest = max(newest, os.path.getmtime(dirpath))
            for name in filenames:
                newest = max(newest,
                             os.path.getmtime(os.path.join(dirpath, name)))
    except OSError:
        # Entries vanishing mid-walk mean someone is actively mutating
        # the tree — treat it as freshly written.
        return time.time()
    return newest


class CheckpointIOError(RuntimeError):
    """Checkpoint I/O failed after exhausting retries.

    The checkpoint directory layout is still consistent: a failed save
    leaves only a tmp dir (the previous checkpoints are untouched).
    """


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed validation (truncated write, bad checksum,
    unreadable orbax state). Carries the offending path and reason so
    the caller can fall back to an older checkpoint or re-save."""

    def __init__(self, path, reason):
        super().__init__(f"corrupt checkpoint at {path}: {reason}")
        self.path = path
        self.reason = reason


def _leaf_checksums(state):
    """crc32 + dtype/shape per leaf, keyed by pytree key-path."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        arr = np.asarray(leaf)
        out[jax.tree_util.keystr(path)] = {
            "crc32": zlib.crc32(np.ascontiguousarray(arr).tobytes()),
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
        }
    return out


def _file_inventory(root, skip={MANIFEST_NAME}):
    inv = {}
    for dirpath, _, filenames in os.walk(root):
        for name in filenames:
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root)
            if rel in skip:
                continue
            inv[rel] = os.path.getsize(full)
    return inv


class CheckpointManager:
    def __init__(self, save_dir=None, keep_last_n=0, async_save=False,
                 io_retries=3, io_retry_base_s=0.05, io_timeout_s=None,
                 process_index=None, process_count=None,
                 tmp_gc_grace_s=TMP_GC_GRACE_S):
        self.save_dir = os.path.abspath(save_dir) if save_dir else None
        self.keep_last_n = int(keep_last_n)
        self.async_save = bool(async_save)
        self.io_retries = int(io_retries)
        self.io_retry_base_s = float(io_retry_base_s)
        self.io_timeout_s = io_timeout_s
        self.tmp_gc_grace_s = float(tmp_gc_grace_s)
        self._pi = jax.process_index() if process_index is None \
            else process_index
        self._pc = jax.process_count() if process_count is None \
            else process_count
        self._pool = None
        self._pending = None
        self._active_tmp = None

    # ------------------------------------------------------------------
    # paths
    # ------------------------------------------------------------------
    @staticmethod
    def ckpt_path(save_dir, tag):
        return os.path.abspath(os.path.join(save_dir, str(tag)))

    @staticmethod
    def _tmp_path(save_dir, tag):
        # Deterministic (no pid/timestamp): a crashed attempt's leftover
        # is simply overwritten by the retry of the same tag.
        return os.path.abspath(os.path.join(save_dir, TMP_PREFIX + str(tag)))

    def _retry(self, fn, what):
        try:
            return retry_with_backoff(
                fn, what=what, attempts=self.io_retries,
                base_delay_s=self.io_retry_base_s,
                timeout_s=self.io_timeout_s, retry_on=(OSError,))
        except RetryExhaustedError as e:
            raise CheckpointIOError(str(e)) from e

    # ------------------------------------------------------------------
    # save
    # ------------------------------------------------------------------
    def save(self, save_dir, tag, state, meta, save_latest=True,
             async_save=None, extra_manifest=None, fault_op="save"):
        """Atomically write one checkpoint; returns its final path.

        ``state`` is the engine's array pytree (orbax payload), ``meta``
        a JSON-serializable dict. ``extra_manifest`` (JSON-serializable)
        is merged into manifest.json — the engine records its
        ``topology``/``arrays`` sections there so checkpoints are
        self-describing (`runtime/elastic/topology.py`). ``fault_op``
        names the fault-injection seam probed at the worst-case
        interrupt point ("save" for engine saves, "reshard" for the
        offline resharder). With async enabled the state is snapshotted
        to host numpy before returning (safe against the engine's
        donated device buffers) and the I/O happens on a background
        worker — call :meth:`wait` to join it.
        """
        self.wait()  # surface a previous async failure before overwriting
        use_async = self.async_save if async_save is None else async_save
        if use_async:
            # np.array(copy=True), not np.asarray: leaves that are ALREADY
            # host numpy (the offload path's master-buffer views) would
            # otherwise alias live memory the next train step mutates.
            state = jax.tree_util.tree_map(
                lambda x: np.array(jax.device_get(x), copy=True), state)
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix="ckpt_save")
            self._pending = self._pool.submit(
                self._save_sync, save_dir, tag, state, meta, save_latest,
                extra_manifest, fault_op)
            return self.ckpt_path(save_dir, tag)
        return self._save_sync(save_dir, tag, state, meta, save_latest,
                               extra_manifest, fault_op)

    def wait(self):
        """Join an in-flight async save, raising its error if it failed."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def _save_sync(self, save_dir, tag, state, meta, save_latest,
                   extra_manifest=None, fault_op="save"):
        save_dir = os.path.abspath(save_dir)
        final = self.ckpt_path(save_dir, tag)
        tmp = self._tmp_path(save_dir, tag)

        def write():
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp, exist_ok=True)
            self._active_tmp = tmp
            import orbax.checkpoint as ocp
            ocp.PyTreeCheckpointer().save(
                os.path.join(tmp, STATE_SUBDIR), state, force=True)
            # Worst-case interrupt point for the harness: state is on
            # disk but the checkpoint is not yet valid or published.
            fault_injection.maybe_fail_io(fault_op)
            fault_injection.maybe_kill("checkpoint_save")
            if self._pi == 0:
                with open(os.path.join(tmp, META_NAME), "w") as f:
                    json.dump(meta, f)
                manifest = dict(extra_manifest or {})
                manifest.update({
                    "format_version": MANIFEST_VERSION,
                    "tag": str(tag),
                    "global_steps": meta.get("global_steps"),
                    "inventory": _file_inventory(tmp),
                    # Multi-process arrays are not fully addressable on
                    # any one host — inventory-only integrity there.
                    "checksums": _leaf_checksums(state)
                    if self._pc == 1 else None,
                })
                with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
                    json.dump(manifest, f)
                if os.path.isdir(final):
                    shutil.rmtree(final)
                os.rename(tmp, final)

        try:
            self._retry(write, what=f"checkpoint save {final}")
        finally:
            self._active_tmp = None
        if save_latest and self._pi == 0:
            self._retry(lambda: self._write_latest(save_dir, tag),
                        what=f"latest pointer {save_dir}")
        if self.keep_last_n > 0 and self._pi == 0:
            self._gc(save_dir, keep=self.keep_last_n)
        return final

    @staticmethod
    def _write_latest(save_dir, tag):
        tmp = os.path.join(save_dir, LATEST_NAME + ".tmp")
        with open(tmp, "w") as f:
            f.write(str(tag))
        os.replace(tmp, os.path.join(save_dir, LATEST_NAME))

    # ------------------------------------------------------------------
    # discovery + validation
    # ------------------------------------------------------------------
    def list_checkpoints(self, save_dir):
        """(tag, global_steps, path) for every complete checkpoint dir,
        newest first. Tmp dirs and entries without a readable manifest
        rank by mtime with global_steps=None (they sort oldest)."""
        save_dir = os.path.abspath(save_dir)
        if not os.path.isdir(save_dir):
            return []
        out = []
        for name in os.listdir(save_dir):
            path = os.path.join(save_dir, name)
            if not os.path.isdir(path) or name.startswith(TMP_PREFIX):
                continue
            steps = None
            try:
                with open(os.path.join(path, MANIFEST_NAME)) as f:
                    steps = json.load(f).get("global_steps")
            except (OSError, ValueError):
                try:
                    with open(os.path.join(path, META_NAME)) as f:
                        steps = json.load(f).get("global_steps")
                except (OSError, ValueError):
                    pass
            out.append((name, steps, path))
        out.sort(key=lambda t: (t[1] is not None, t[1] or 0,
                                os.path.getmtime(t[2])), reverse=True)
        return out

    def validate(self, path):
        """Cheap structural validation; raises CheckpointCorruptError.

        Checks directory shape (state/, meta.json, manifest.json) and
        that every manifest-inventory file exists with its recorded
        size — catches truncated/partial writes without reading arrays.
        Array-level corruption is caught at load time via checksums.
        """
        if not os.path.isdir(path):
            raise CheckpointCorruptError(path, "not a directory")
        if not os.path.isdir(os.path.join(path, STATE_SUBDIR)):
            raise CheckpointCorruptError(path, "missing state/ subdir")
        try:
            with open(os.path.join(path, META_NAME)) as f:
                json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                path, f"missing/unreadable {META_NAME} ({e})") from e
        try:
            with open(os.path.join(path, MANIFEST_NAME)) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                path, f"missing/unreadable {MANIFEST_NAME} ({e})") from e
        for rel, size in (manifest.get("inventory") or {}).items():
            full = os.path.join(path, rel)
            if not os.path.isfile(full):
                raise CheckpointCorruptError(
                    path, f"inventory file missing: {rel}")
            actual = os.path.getsize(full)
            if actual != size:
                raise CheckpointCorruptError(
                    path, f"inventory size mismatch for {rel}: "
                    f"manifest says {size} bytes, found {actual}")
        return manifest

    def is_valid(self, path):
        try:
            self.validate(path)
            return True
        except CheckpointCorruptError as e:
            logger.warning("skipping invalid checkpoint: %s", e)
            return False

    def resolve_tag(self, load_dir, tag=None):
        """Resolve which checkpoint to load; None if nothing valid.

        An explicit ``tag`` is strict (its checkpoint must validate —
        the caller asked for *that* one). ``tag=None`` tries the
        ``latest`` pointer first, then falls back to scanning for the
        newest checkpoint that passes validation. Falling back past one
        or more corrupt/incomplete checkpoints emits a durable
        ``checkpoint_fallback`` telemetry event recording which tags
        were skipped and why — silently resuming from an older step is
        exactly the kind of surprise postmortems need to see.
        """
        load_dir = os.path.abspath(load_dir)
        if tag is not None:
            self.validate(self.ckpt_path(load_dir, tag))
            return str(tag)
        skipped = []
        tried = set()

        def usable(name, path):
            if name in tried:
                return False
            tried.add(name)
            try:
                self.validate(path)
                return True
            except CheckpointCorruptError as e:
                logger.warning("skipping invalid checkpoint: %s", e)
                skipped.append({"tag": str(name),
                                "error": type(e).__name__,
                                "reason": str(e.reason)})
                return False

        resolved = None
        pointed = None
        latest = os.path.join(load_dir, LATEST_NAME)
        if os.path.isfile(latest):
            with open(latest) as f:
                pointed = f.read().strip()
            if pointed and usable(pointed, self.ckpt_path(load_dir, pointed)):
                return pointed
            logger.warning(
                "latest pointer %r is stale or its checkpoint is invalid; "
                "scanning %s for the newest valid checkpoint",
                pointed, load_dir)
        for name, _, path in self.list_checkpoints(load_dir):
            if usable(name, path):
                resolved = name
                break
        if skipped:
            self._emit_fallback(load_dir, resolved, skipped)
        return resolved

    @staticmethod
    def _emit_fallback(load_dir, resolved, skipped):
        try:
            from deepspeed_tpu.telemetry.session import get_default_session
            session = get_default_session()
            if session is None:
                return
            session.emit("checkpoint_fallback",
                         dir=load_dir,
                         resolved_tag=resolved,
                         skipped=len(skipped),
                         checkpoints=skipped[:8])
        except Exception:
            logger.debug("checkpoint_fallback event emission failed",
                         exc_info=True)

    # ------------------------------------------------------------------
    # load
    # ------------------------------------------------------------------
    def load(self, load_dir, tag):
        """Restore one validated checkpoint as a host-numpy pytree.

        Returns ``(state, meta, path)``. Orbax/IO failures and checksum
        mismatches raise :class:`CheckpointCorruptError` instead of an
        opaque orbax traceback.
        """
        path = self.ckpt_path(load_dir, tag)
        manifest = self.validate(path)
        fault_injection.maybe_fail_io("load")

        import orbax.checkpoint as ocp

        def restore():
            ckptr = ocp.PyTreeCheckpointer()
            state_path = os.path.join(path, STATE_SUBDIR)
            # Restore as host numpy (placement happens in the engine on
            # the CURRENT mesh/shardings) — restoring with the saved
            # shardings trips orbax's different-topology path, which is
            # exactly the elastic/restage case the engine supports.
            meta = ckptr.metadata(state_path)
            item_meta = getattr(meta, "item_metadata", meta)
            restore_args = jax.tree_util.tree_map(
                lambda _: ocp.RestoreArgs(restore_type=np.ndarray),
                item_meta)
            return ckptr.restore(state_path, restore_args=restore_args)

        try:
            state = self._retry(restore, what=f"checkpoint restore {path}")
        except CheckpointIOError:
            raise
        except Exception as e:
            raise CheckpointCorruptError(
                path, f"orbax restore failed ({type(e).__name__}: {e}); "
                "checkpoint state is unreadable") from e

        checksums = manifest.get("checksums")
        if checksums is not None and self._pc == 1:
            self._verify_checksums(path, state, checksums)

        with open(os.path.join(path, META_NAME)) as f:
            meta = json.load(f)
        return state, meta, path

    @staticmethod
    def _verify_checksums(path, state, checksums):
        actual = _leaf_checksums(state)
        if set(actual) != set(checksums):
            missing = sorted(set(checksums) - set(actual))
            extra = sorted(set(actual) - set(checksums))
            raise CheckpointCorruptError(
                path, f"state tree structure differs from manifest "
                f"(missing leaves: {missing[:4]}, extra: {extra[:4]})")
        for key, rec in checksums.items():
            got = actual[key]
            if got["crc32"] != rec["crc32"]:
                raise CheckpointCorruptError(
                    path, f"checksum mismatch for leaf {key}: array bytes "
                    "changed on disk since save")

    # ------------------------------------------------------------------
    # retention GC
    # ------------------------------------------------------------------
    def _gc(self, save_dir, keep):
        ckpts = self.list_checkpoints(save_dir)
        for name, _, path in ckpts[keep:]:
            try:
                shutil.rmtree(path)
                logger.info("retention GC removed checkpoint %s", path)
            except OSError as e:
                logger.warning("retention GC failed for %s: %s", path, e)
        # Leftover tmp dirs from crashed attempts are dead weight too —
        # but on a shared filesystem a ``.tmp.<tag>`` dir may be ANOTHER
        # process's async save that is still being written (process 0
        # runs GC while peers stream orbax shards into their tmp dirs).
        # Only reap a tmp dir that (a) is not this manager's in-flight
        # write, (b) does not belong to a checkpoint we are keeping, and
        # (c) has gone quiet for the full grace window — an active
        # writer keeps refreshing mtimes somewhere in the tree.
        live = {t for t, _, _ in ckpts[:keep]}
        now = time.time()
        for name in os.listdir(save_dir):
            if not name.startswith(TMP_PREFIX):
                continue
            path = os.path.join(save_dir, name)
            if path == self._active_tmp:
                continue
            if name[len(TMP_PREFIX):] in live:
                continue
            if now - _newest_mtime(path) < self.tmp_gc_grace_s:
                logger.info(
                    "retention GC keeping recent tmp dir %s "
                    "(may be a peer's in-flight save)", path)
                continue
            shutil.rmtree(path, ignore_errors=True)
            logger.info("retention GC removed stale tmp dir %s", path)

    def close(self):
        self.wait()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
