"""Bounded retry-with-backoff for host-side I/O and worker futures.

Checkpoint I/O (orbax writes, manifest/meta json, directory renames) and
the ZeRO-Offload host-Adam futures are the two places the engine blocks
on work that can fail transiently (filesystem hiccups on preempted pods,
worker-thread exceptions). Both get the same policy: a bounded number of
attempts with exponential backoff and an overall deadline, after which a
typed :class:`RetryExhaustedError` carries the last underlying failure.
"""

import logging
import time

logger = logging.getLogger(__name__)


class RetryExhaustedError(RuntimeError):
    """All retry attempts failed (or the overall deadline expired).

    ``last_error`` holds the final underlying exception; it is also
    chained as ``__cause__`` so tracebacks stay actionable.
    """

    def __init__(self, what, attempts, last_error):
        super().__init__(
            f"{what} failed after {attempts} attempt(s): "
            f"{type(last_error).__name__}: {last_error}"
        )
        self.attempts = attempts
        self.last_error = last_error


class HostAdamError(RuntimeError):
    """A ZeRO-Offload host-Adam worker raised and retries were exhausted.

    Raised instead of letting the raw worker exception surface from a
    future so callers can distinguish an optimizer-worker failure (host
    state may be mid-update) from ordinary training errors.
    """


def retry_with_backoff(fn, *, what, attempts=3, base_delay_s=0.05,
                       timeout_s=None, retry_on=(Exception,),
                       sleep=time.sleep, clock=time.monotonic):
    """Call ``fn()`` with up to ``attempts`` tries and exponential backoff.

    ``timeout_s`` bounds the total wall time across attempts (checked
    before each retry sleep; a started attempt is never interrupted).
    Non-``retry_on`` exceptions propagate immediately. ``sleep``/``clock``
    are injectable for tests.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    deadline = None if timeout_s is None else clock() + timeout_s
    last = None
    for i in range(attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 - retry loop
            last = e
            remaining = attempts - 1 - i
            if remaining == 0:
                break
            if deadline is not None and clock() >= deadline:
                logger.warning("%s: deadline expired after attempt %d/%d",
                               what, i + 1, attempts)
                break
            delay = base_delay_s * (2 ** i)
            if deadline is not None:
                delay = min(delay, max(0.0, deadline - clock()))
            logger.warning("%s: attempt %d/%d failed (%s: %s); retrying in %.3fs",
                           what, i + 1, attempts, type(e).__name__, e, delay)
            sleep(delay)
    raise RetryExhaustedError(what, i + 1, last) from last


def future_result_with_retry(submit, *, what, attempts=3,
                             base_delay_s=0.05, timeout_s=None):
    """Drain a worker future, resubmitting on failure.

    ``submit`` is a zero-arg callable that (re)submits the work and
    returns a ``concurrent.futures.Future``; each attempt waits on a
    fresh future so a failed submission can be retried. Exactly-once
    semantics are the caller's responsibility — only pass work that is
    safe to resubmit (e.g. host-Adam range updates that failed before
    mutating the master buffers). Raises :class:`HostAdamError` (chained
    to a :class:`RetryExhaustedError`) when attempts run out.
    """
    def attempt():
        fut = submit()
        return fut.result(timeout=timeout_s)

    try:
        return retry_with_backoff(attempt, what=what, attempts=attempts,
                                  base_delay_s=base_delay_s,
                                  timeout_s=timeout_s)
    except RetryExhaustedError as e:
        raise HostAdamError(str(e)) from e
