"""Resilience subsystem: preemption-safe checkpointing, auto-resume,
step health guards, and deterministic fault injection.

The seed reproduced DeepSpeed v0.3.2's *training* capabilities; this
package adds its *operational* ones — the parts a preemptible TPU pod
slice needs to survive long runs:

- :mod:`checkpoint` — atomic (tmp-dir + rename) checkpoint writes with a
  per-array checksum manifest, retention GC, load-time validation and
  newest-valid fallback, retry-with-backoff around all I/O, optional
  async saves.
- :mod:`guards` — step health guards (NaN/Inf gradients, loss-spike
  circuit breaker, loss-scale collapse) with configurable actions
  (``warn | skip_step | rollback_to_checkpoint | abort``).
- :mod:`preemption` — SIGTERM-driven save-and-exit between steps.
- :mod:`hotckpt` — the in-memory hot-checkpoint tier: frequent CRC-
  stamped device→host snapshots (optionally mirrored to local disk)
  that the engine's restore ladder tries before any disk checkpoint.
- :mod:`fault_injection` — deterministic fault hooks (NaN grads,
  mid-write I/O failures, simulated preemption, hangs, hard SIGKILLs,
  host-Adam worker exceptions) for testing failure behavior.
- :mod:`retry` — bounded retry-with-backoff used by checkpoint I/O and
  the offload host-Adam futures.
"""

from deepspeed_tpu.runtime.resilience.checkpoint import (
    CheckpointCorruptError,
    CheckpointIOError,
    CheckpointManager,
)
from deepspeed_tpu.runtime.resilience.hotckpt import (
    HotCheckpointCorruptError,
    HotCheckpointStore,
    HotSnapshot,
)
from deepspeed_tpu.runtime.resilience.guards import (
    ACTION_ABORT,
    ACTION_ROLLBACK,
    ACTION_SKIP_STEP,
    ACTION_WARN,
    GuardTrip,
    HealthGuardAbort,
    StepHealthMonitor,
)
from deepspeed_tpu.runtime.resilience.preemption import (
    PreemptedError,
    PreemptionHandler,
)
from deepspeed_tpu.runtime.resilience.retry import (
    HostAdamError,
    RetryExhaustedError,
    retry_with_backoff,
    future_result_with_retry,
)

__all__ = [
    "CheckpointCorruptError",
    "CheckpointIOError",
    "CheckpointManager",
    "HotCheckpointCorruptError",
    "HotCheckpointStore",
    "HotSnapshot",
    "ACTION_ABORT",
    "ACTION_ROLLBACK",
    "ACTION_SKIP_STEP",
    "ACTION_WARN",
    "GuardTrip",
    "HealthGuardAbort",
    "StepHealthMonitor",
    "PreemptedError",
    "PreemptionHandler",
    "HostAdamError",
    "RetryExhaustedError",
    "retry_with_backoff",
    "future_result_with_retry",
]
