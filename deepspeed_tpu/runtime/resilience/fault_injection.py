"""Deterministic fault injection for resilience testing.

A process-global registry of *armed* faults that production code probes
at well-defined seams. Every probe is a no-op unless a test armed the
matching fault, and the engine only wires the gradient-fault hook into
its compiled step when ``resilience.fault_injection.enabled`` is set in
config — injection cannot perturb ordinary runs.

Seams (all deterministic — armed for explicit steps or a fixed count):

- ``nan_grads`` — :func:`grad_fault_value` returns NaN for armed steps;
  the engine multiplies it into the gradients inside the compiled step.
- ``io_failure`` — :func:`maybe_fail_io` raises ``InjectedIOError``
  from inside checkpoint I/O, *after* partial data has been written and
  *before* the atomic rename (the worst-case interrupt point).
- ``preemption`` — :func:`preemption_due` tells the engine to deliver
  SIGTERM to itself between steps, exercising the real signal path.
- ``host_adam`` — :func:`maybe_fail_host_adam` raises
  ``InjectedHostAdamError`` at future-submission time, before the C++
  kernel touches the master buffers, so a retry is exact.
- ``hang`` — :func:`hang_seconds` tells the engine to sleep on the host
  *inside* the dispatch span at the armed step, simulating a stuck
  collective/straggler so the hang watchdog
  (`telemetry/watchdog.py`) can be exercised end to end.
- ``kill`` — :func:`maybe_kill` delivers a hard signal (default SIGKILL)
  to the process itself, either mid-step (inside the dispatch span,
  after the batch is consumed and before the optimizer state is
  consistent), mid-checkpoint-save (state bytes staged, manifest not
  yet sealed), or mid-decode-step (``op="decode_step"`` — inside a
  serving replica's decode loop, with in-flight sessions whose KV lives
  only in that process) — the ungraceful exits the ``ds_tpu_run``
  supervisor (`runtime/supervisor/`) and the serving fleet router
  (`inference/fleet.py`) must detect and recover from. Unlike every
  other seam this one never raises: the process just dies, exactly like
  an OOM-killer or preempted-VM death.

Serving seams (the fleet resilience ladder, ISSUE 17):

- ``decode_exception`` — :func:`maybe_fail_decode` raises
  ``InjectedDecodeError`` from inside the continuous-batching
  scheduler's decode step: the softer replica death (the process gets
  to crash with a traceback and a nonzero exit, unlike ``kill``).
- ``page_corruption`` — :func:`corrupt_host_pages` tells the host page
  tier (`inference/paging.py:HostPageStore`) to flip a byte in a parked
  session's snapshot AFTER its CRCs are stamped, so the next page-in
  detects the rot and raises ``HostPageCorruptError`` — exercising the
  drop-pages-and-re-prefill recovery path.
- ``heartbeat_stall`` — :func:`heartbeat_stall_seconds` tells a serving
  replica worker to STOP writing its ``hb-p<idx>.json`` heartbeat for N
  seconds while continuing to decode: the replica looks dead to the
  router's liveness deadline without actually being dead, the
  classification the hang/stale path must get right.

Use :func:`clear_faults` (or the ``fault_registry`` pytest fixture in
``tests/``) to disarm everything between tests. Subprocess serving
replicas arm faults from the ``DS_TPU_SERVE_INJECT`` env var via
:func:`arm_from_env` — only on their first attempt, matching the
``DS_TPU_RUN_RESTART_COUNT`` contract.
"""

import os
import signal
import threading

import numpy as np

_lock = threading.Lock()
_faults = {}


class InjectedIOError(OSError):
    """Checkpoint I/O failure injected by the fault harness."""


class InjectedDecodeError(RuntimeError):
    """Decode-step failure injected into a serving replica's scheduler
    loop by the fault harness. Deliberately NOT caught inside the
    replica: a decode-step exception is a replica crash, and the fleet
    router must observe the nonzero exit and redispatch."""


class InjectedHostAdamError(RuntimeError):
    """Host-Adam worker failure injected by the fault harness.

    Raised by the probe BEFORE the C++ kernel runs, so the master/moment
    buffers are untouched and a resubmission is exact — which is what
    ``host_state_clean`` asserts to the retry wrapper.
    """

    host_state_clean = True


def clear_faults():
    """Disarm all faults."""
    with _lock:
        _faults.clear()


def active_faults():
    """Names of currently armed faults (for assertions in tests)."""
    with _lock:
        return sorted(_faults)


def _pop_if_exhausted(name, entry):
    if entry.get("times") is not None and entry["times"] <= 0:
        _faults.pop(name, None)


# --------------------------------------------------------------------------
# NaN gradients
# --------------------------------------------------------------------------

def inject_nan_grads(at_steps):
    """Arm NaN gradients for the given engine global steps (0-based)."""
    with _lock:
        _faults["nan_grads"] = {"at_steps": set(int(s) for s in at_steps)}


def grad_fault_value(step):
    """Multiplier folded into grads at ``step``: NaN if armed, else 1.0."""
    with _lock:
        entry = _faults.get("nan_grads")
        if entry is not None and int(step) in entry["at_steps"]:
            return np.float32(np.nan)
    return np.float32(1.0)


# --------------------------------------------------------------------------
# Checkpoint I/O failures
# --------------------------------------------------------------------------

def inject_io_failure(op="save", times=1):
    """Arm ``times`` consecutive failures of checkpoint ``op``
    ("save"/"load"/"reshard")."""
    with _lock:
        _faults[f"io_failure:{op}"] = {"times": int(times)}


def inject_reshard_failure(times=1):
    """Arm ``times`` consecutive mid-reshard I/O failures.

    The probe fires inside the resharder's target write, after the state
    bytes are staged and before the manifest seal + atomic rename — the
    worst-case interrupt: the source checkpoint must stay intact and the
    partial target must be garbage-collected.
    """
    inject_io_failure("reshard", times=times)


def maybe_fail_io(op):
    """Probe called from inside checkpoint I/O; raises if armed."""
    with _lock:
        name = f"io_failure:{op}"
        entry = _faults.get(name)
        if entry is None:
            return
        entry["times"] -= 1
        _pop_if_exhausted(name, entry)
    raise InjectedIOError(f"injected checkpoint {op} failure")


# --------------------------------------------------------------------------
# Preemption
# --------------------------------------------------------------------------

def simulate_preemption(at_step):
    """Arm a simulated preemption (SIGTERM) before engine step ``at_step``."""
    with _lock:
        _faults["preemption"] = {"at_step": int(at_step)}


def preemption_due(step):
    """True exactly once, when ``step`` reaches the armed preemption point."""
    with _lock:
        entry = _faults.get("preemption")
        if entry is not None and int(step) >= entry["at_step"]:
            _faults.pop("preemption", None)
            return True
    return False


# --------------------------------------------------------------------------
# Hangs (stuck collective / straggler simulation)
# --------------------------------------------------------------------------

def inject_hang(at_step, seconds):
    """Arm a one-shot host-side sleep of ``seconds`` inside the dispatch
    phase of engine global step ``at_step``."""
    with _lock:
        _faults["hang"] = {"at_step": int(at_step),
                           "seconds": float(seconds)}


def hang_seconds(step):
    """Seconds the engine should sleep at ``step`` (0.0 = not armed).
    Fires exactly once, at the first step >= the armed point."""
    with _lock:
        entry = _faults.get("hang")
        if entry is not None and int(step) >= entry["at_step"]:
            _faults.pop("hang", None)
            return entry["seconds"]
    return 0.0


# --------------------------------------------------------------------------
# Hard process death (SIGKILL mid-step / mid-checkpoint-save)
# --------------------------------------------------------------------------

KILL_OPS = ("step", "checkpoint_save", "decode_step", "prefill_chunk")


def inject_kill(op="step", at_step=None, signum=signal.SIGKILL):
    """Arm a hard self-delivered signal at a worst-case point.

    ``op="step"`` fires inside the dispatch span of the first engine
    global step >= ``at_step``; ``op="checkpoint_save"`` fires inside
    the checkpoint writer after the state bytes are staged and before
    the manifest seal + atomic rename (``at_step`` is ignored there —
    the next save dies); ``op="decode_step"`` fires inside a serving
    replica's decode loop at the first scheduler step >= ``at_step``,
    with admitted sessions' KV still device-resident and un-drained;
    ``op="prefill_chunk"`` fires inside the engine's chunked-prefill
    host loop at the first chunk index >= ``at_step`` — mid-prompt,
    with the row's pages allocated and partially written (the
    disaggregated prefill-tier worst case).
    The default SIGKILL cannot be caught, so no preemption handler,
    atexit hook, or flight recorder runs: this is the ungraceful-exit
    seam the supervisor and fleet soak tests need.
    """
    if op not in KILL_OPS:
        raise ValueError(f"kill op must be one of {KILL_OPS}, got {op!r}")
    with _lock:
        _faults[f"kill:{op}"] = {
            "at_step": None if at_step is None else int(at_step),
            "signum": int(signum),
        }


def maybe_kill(op, step=None):
    """Probe called at the kill seams; delivers the armed signal to this
    process (and for SIGKILL never returns)."""
    with _lock:
        entry = _faults.get(f"kill:{op}")
        if entry is None:
            return
        if entry["at_step"] is not None and (
                step is None or int(step) < entry["at_step"]):
            return
        _faults.pop(f"kill:{op}", None)
        signum = entry["signum"]
    os.kill(os.getpid(), signum)


# --------------------------------------------------------------------------
# Serving: decode-step exceptions (soft replica crash)
# --------------------------------------------------------------------------

def inject_decode_exception(at_step, times=1):
    """Arm ``times`` decode-step exceptions starting at the first
    scheduler step >= ``at_step`` (serving replica soft-crash seam)."""
    with _lock:
        _faults["decode_exception"] = {"at_step": int(at_step),
                                       "times": int(times)}


def maybe_fail_decode(step):
    """Probe called from inside the scheduler's decode step; raises
    :class:`InjectedDecodeError` while armed."""
    with _lock:
        entry = _faults.get("decode_exception")
        if entry is None or int(step) < entry["at_step"]:
            return
        entry["times"] -= 1
        _pop_if_exhausted("decode_exception", entry)
    raise InjectedDecodeError(
        f"injected decode-step failure at step {step}")


# --------------------------------------------------------------------------
# Serving: host page-tier corruption (silent rot between park and resume)
# --------------------------------------------------------------------------

def inject_page_corruption(session_id=None, times=1):
    """Arm host-page corruption: the next ``times`` sessions parked to
    the host tier (or only ``session_id``'s parks, when given) get one
    byte flipped AFTER their CRCs are stamped, so resume detects it."""
    with _lock:
        _faults["page_corruption"] = {
            "session_id": session_id, "times": int(times)}


def corrupt_host_pages(session_id):
    """Probe called by the host page store at park time; True when the
    harness wants this session's snapshot corrupted."""
    with _lock:
        entry = _faults.get("page_corruption")
        if entry is None:
            return False
        if entry["session_id"] is not None and \
                entry["session_id"] != session_id:
            return False
        entry["times"] -= 1
        _pop_if_exhausted("page_corruption", entry)
    return True


# --------------------------------------------------------------------------
# Serving: heartbeat stall (replica looks dead without dying)
# --------------------------------------------------------------------------

def inject_heartbeat_stall(at_step, seconds):
    """Arm a one-shot heartbeat blackout: from the first scheduler step
    >= ``at_step`` the replica worker suppresses heartbeat writes for
    ``seconds`` while continuing to serve."""
    with _lock:
        _faults["heartbeat_stall"] = {"at_step": int(at_step),
                                      "seconds": float(seconds)}


def heartbeat_stall_seconds(step):
    """Seconds the replica should suppress heartbeat writes starting at
    ``step`` (0.0 = not armed). Fires exactly once."""
    with _lock:
        entry = _faults.get("heartbeat_stall")
        if entry is not None and int(step) >= entry["at_step"]:
            _faults.pop("heartbeat_stall", None)
            return entry["seconds"]
    return 0.0


# --------------------------------------------------------------------------
# Env-var arming (subprocess serving replicas)
# --------------------------------------------------------------------------

INJECT_ENV = "DS_TPU_SERVE_INJECT"


def arm_from_env(env=None):
    """Arm faults described by the ``DS_TPU_SERVE_INJECT`` env var — a
    JSON object like ``{"kill": {"op": "decode_step", "at_step": 4},
    "decode_exception": {"at_step": 2}, "heartbeat_stall": {"at_step":
    3, "seconds": 30}, "page_corruption": {}}``. Subprocess replica
    workers call this on startup (first attempt only); returns the list
    of armed fault names."""
    import json
    raw = (env if env is not None else os.environ).get(INJECT_ENV)
    if not raw:
        return []
    spec = json.loads(raw)
    armed = []
    if "kill" in spec:
        k = spec["kill"] or {}
        inject_kill(op=k.get("op", "decode_step"),
                    at_step=k.get("at_step"),
                    signum=int(k.get("signum", signal.SIGKILL)))
        armed.append("kill")
    if "decode_exception" in spec:
        d = spec["decode_exception"] or {}
        inject_decode_exception(at_step=d.get("at_step", 0),
                                times=d.get("times", 1))
        armed.append("decode_exception")
    if "heartbeat_stall" in spec:
        h = spec["heartbeat_stall"] or {}
        inject_heartbeat_stall(at_step=h.get("at_step", 0),
                               seconds=h.get("seconds", 60.0))
        armed.append("heartbeat_stall")
    if "page_corruption" in spec:
        p = spec["page_corruption"] or {}
        inject_page_corruption(session_id=p.get("session_id"),
                               times=p.get("times", 1))
        armed.append("page_corruption")
    return armed


# --------------------------------------------------------------------------
# Host-Adam worker failures
# --------------------------------------------------------------------------

def inject_host_adam_failure(times=1):
    """Arm ``times`` consecutive host-Adam submission failures."""
    with _lock:
        _faults["host_adam"] = {"times": int(times)}


def maybe_fail_host_adam():
    """Probe called at host-Adam submission time; raises if armed."""
    with _lock:
        entry = _faults.get("host_adam")
        if entry is None:
            return
        entry["times"] -= 1
        _pop_if_exhausted("host_adam", entry)
    raise InjectedHostAdamError("injected host-Adam worker failure")
