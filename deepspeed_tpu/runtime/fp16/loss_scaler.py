"""Static and dynamic loss scaling.

TPU-native analog of the reference's ``LossScaler``/``DynamicLossScaler``
(`runtime/fp16/loss_scaler.py:56,79`). Semantics are identical (scale factor,
scale window, min scale, delayed-shift hysteresis, consecutive hysteresis),
but the state is an immutable pytree and ``update_scale`` is a pure function,
so the overflow-driven skip/update decision can live inside the jitted train
step as a ``jnp.where`` instead of host control flow.

On TPU, fp16 dynamic loss scaling is mostly needed for strict parity runs;
bf16 (the native TPU dtype) needs no scaling and maps to the static scaler
with scale 1.
"""

from typing import NamedTuple

import jax.numpy as jnp


class LossScaleState(NamedTuple):
    """Immutable dynamic-loss-scale state (device-resident, jit-friendly)."""
    cur_scale: jnp.ndarray        # f32 scalar
    cur_iter: jnp.ndarray         # i32 scalar
    last_overflow_iter: jnp.ndarray  # i32 scalar
    cur_hysteresis: jnp.ndarray   # i32 scalar


def init_loss_scale_state(init_scale=2 ** 32, delayed_shift=1):
    return LossScaleState(
        cur_scale=jnp.asarray(init_scale, jnp.float32),
        cur_iter=jnp.asarray(0, jnp.int32),
        last_overflow_iter=jnp.asarray(-1, jnp.int32),
        cur_hysteresis=jnp.asarray(delayed_shift, jnp.int32),
    )


def update_loss_scale(state: LossScaleState,
                      overflow,
                      scale_factor=2.0,
                      scale_window=1000,
                      min_scale=1.0,
                      delayed_shift=1,
                      consecutive_hysteresis=False) -> LossScaleState:
    """Pure version of DynamicLossScaler.update_scale (reference :151-166)."""
    overflow = jnp.asarray(overflow)

    # --- overflow branch ---
    shift_now = jnp.logical_or(delayed_shift == 1, state.cur_hysteresis == 1)
    scale_on_overflow = jnp.where(
        shift_now,
        jnp.maximum(state.cur_scale / scale_factor, min_scale),
        state.cur_scale)
    hysteresis_on_overflow = jnp.where(shift_now, state.cur_hysteresis,
                                       state.cur_hysteresis - 1)

    # --- no-overflow branch ---
    window_hit = (state.cur_iter - state.last_overflow_iter) % scale_window == 0
    scale_on_ok = jnp.where(window_hit, state.cur_scale * scale_factor,
                            state.cur_scale)
    if consecutive_hysteresis:
        hysteresis_on_ok = jnp.asarray(delayed_shift, jnp.int32)
    else:
        hysteresis_on_ok = jnp.where(window_hit, delayed_shift,
                                     state.cur_hysteresis).astype(jnp.int32)

    return LossScaleState(
        cur_scale=jnp.where(overflow, scale_on_overflow, scale_on_ok),
        cur_iter=state.cur_iter + 1,
        last_overflow_iter=jnp.where(overflow, state.cur_iter,
                                     state.last_overflow_iter),
        cur_hysteresis=jnp.where(overflow, hysteresis_on_overflow,
                                 hysteresis_on_ok).astype(jnp.int32),
    )


def scale_is_collapsed(state: LossScaleState, min_scale=1.0) -> bool:
    """True when the dynamic scale is pinned at its floor — the signal the
    resilience scale-collapse guard counts toward its patience window. A
    scale that reached ``min_scale`` and keeps overflowing means every
    step is being skipped; without intervention the run is dead."""
    return float(jnp.asarray(state.cur_scale)) <= float(min_scale)


class LossScalerBase:
    def __init__(self, cur_scale):
        self.cur_scale = cur_scale

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, grads):
        import jax
        return jax.tree_util.tree_map(lambda g: g * self.loss_scale, grads)

    def update_scale(self, overflow):
        pass

    def backward(self, loss):
        """The reference scales loss before autograd; in JAX, scale the loss
        value that feeds jax.grad (or use engine's built-in scaled loss)."""
        return loss * self.loss_scale


class LossScaler(LossScalerBase):
    """Static loss scale (reference `loss_scaler.py:56`)."""

    def __init__(self, scale=1):
        super().__init__(scale)

    def has_overflow(self, params):
        return False


class DynamicLossScaler(LossScalerBase):
    """Stateful wrapper with reference-identical semantics, backed by the
    pure `update_loss_scale` transition above."""

    def __init__(self,
                 init_scale=2 ** 32,
                 scale_factor=2.0,
                 scale_window=1000,
                 min_scale=1,
                 delayed_shift=1,
                 consecutive_hysteresis=False):
        super().__init__(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.cur_hysteresis = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis

    def _state(self):
        return LossScaleState(
            cur_scale=jnp.asarray(self.cur_scale, jnp.float32),
            cur_iter=jnp.asarray(self.cur_iter, jnp.int32),
            last_overflow_iter=jnp.asarray(self.last_overflow_iter, jnp.int32),
            cur_hysteresis=jnp.asarray(self.cur_hysteresis, jnp.int32),
        )

    def update_scale(self, overflow):
        new = update_loss_scale(self._state(),
                                overflow,
                                scale_factor=self.scale_factor,
                                scale_window=self.scale_window,
                                min_scale=self.min_scale,
                                delayed_shift=self.delayed_shift,
                                consecutive_hysteresis=self.consecutive_hysteresis)
        self.cur_scale = float(new.cur_scale)
        self.cur_iter = int(new.cur_iter)
        self.last_overflow_iter = int(new.last_overflow_iter)
        self.cur_hysteresis = int(new.cur_hysteresis)

    def has_overflow(self, grads):
        import jax
        leaves = jax.tree_util.tree_leaves(grads)
        if not leaves:
            return False
        total = sum(jnp.sum(jnp.logical_not(jnp.isfinite(g))) for g in leaves)
        return bool(total > 0)


def CreateLossScaler(static_loss_scale=None, dynamic_scale_args=None):
    """Factory matching engine usage: static scale → LossScaler, else dynamic."""
    if static_loss_scale is not None and static_loss_scale > 0:
        return LossScaler(scale=static_loss_scale)
    if dynamic_scale_args is not None:
        return DynamicLossScaler(**dynamic_scale_args)
    return DynamicLossScaler()
