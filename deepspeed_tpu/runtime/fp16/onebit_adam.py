"""1-bit Adam: error-feedback momentum-compressed data parallelism.

Capability parity with the reference's ``OnebitAdam``
(`runtime/fp16/onebit_adam.py:18`): a ``freeze_step`` warmup of plain Adam
with dense gradient averaging, then a "compression stage" where the second
moment is frozen and the *momentum* is averaged across data-parallel
workers with error-feedback 1-bit compression
(:func:`deepspeed_tpu.runtime.comm.compressed.compressed_allreduce`).

TPU-native mechanism: where the reference disables the engine's gradient
allreduce (onebit_adam.py:372) and runs an mpi4py/cupy side channel, here
the whole update is one function designed to run inside ``shard_map`` over
the ``data`` mesh axis — local (un-averaged) gradients flow in, the
compressed collective rides ICI/DCN, and the error residuals are explicit
state sharded over the same axis.

Math mirrors the reference exactly: no bias correction, frozen ``v`` after
``freeze_step`` (onebit_adam.py:262-303), update
``m / (sqrt(v) + eps) + wd * p``.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from deepspeed_tpu.runtime.comm.compressed import (
    compressed_allreduce, error_feedback_sizes)

__all__ = ["OnebitAdamState", "init_onebit_state",
           "init_pipeline_onebit_state", "onebit_adam_update"]


class OnebitAdamState(NamedTuple):
    m: Any                      # momentum pytree, fp32, replicated
    v: Any                      # second moment pytree, fp32 (frozen post-warmup)
    step: jnp.ndarray           # i32 — applied steps
    worker_error: jnp.ndarray   # [world, padded_n], shard rank r holds row r
    server_error: jnp.ndarray   # [padded_n], rank r holds its served chunk


def param_count(params):
    return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))


def init_onebit_state(params, world: int) -> OnebitAdamState:
    n = param_count(params)
    padded, _ = error_feedback_sizes(n, world)
    zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
    return OnebitAdamState(
        m=jax.tree_util.tree_map(zeros, params),
        v=jax.tree_util.tree_map(zeros, params),
        step=jnp.asarray(0, jnp.int32),
        worker_error=jnp.zeros((world, padded), jnp.float32),
        server_error=jnp.zeros((padded,), jnp.float32),
    )


def pipeline_mp_mask(params, model):
    """Per-leaf bools in ``tree_leaves(params['body'])`` order: True for
    model-sharded ``mp_*`` leaves. The single source of truth for the 3D
    1-bit layout — both the error-buffer sizing here and the engine's
    group split (`engine.py:_make_pipeline_onebit_train_step`) consume
    it, so the slice offsets cannot drift from the group sizes."""
    from deepspeed_tpu.runtime.pipe.pipeline import _is_mp_leaf
    return [model > 1 and _is_mp_leaf(path, leaf)
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                params["body"])[0]]


def _pipeline_local_sizes(params, num_stages, model=1):
    """(mp_local, rep_local, rest_n): flat element counts as seen by ONE
    (stage, model-rank) device — ``mp_*`` body leaves divide their shard
    dim over ``model``, every other body leaf is model-replicated."""
    mask = pipeline_mp_mask(params, model)
    mp_n = rep_n = 0
    for (path, leaf), is_mp in zip(
            jax.tree_util.tree_flatten_with_path(params["body"])[0], mask):
        if is_mp:
            assert leaf.shape[2] % model == 0, (path, leaf.shape, model)
            mp_n += int(leaf.size) // model
        else:
            rep_n += int(leaf.size)
    rest_n = sum(int(p.size) for k in ("prologue", "epilogue", "tied")
                 for p in jax.tree_util.tree_leaves(params[k]))
    assert mp_n % num_stages == 0 and rep_n % num_stages == 0, (
        mp_n, rep_n, num_stages)
    return mp_n // num_stages, rep_n // num_stages, rest_n


def init_pipeline_onebit_state(params, world: int, num_stages: int,
                               model: int = 1) -> OnebitAdamState:
    """State for the pipeline x 1-bit composition
    (`engine.py:_make_pipeline_onebit_train_step`): m/v mirror the
    (stacked, pipe-sharded) params; error-feedback buffers are per
    (stage[, model-rank], data-rank) over the device-LOCAL flat parameter
    count — every device runs its own compressed collective over ``data``
    within its (stage, model) group, so residuals live where the shards
    live.

    ``params`` is the pipeline tree {prologue, body, epilogue, tied} with
    the body stacked [S, L/S, ...]. Homogeneous stages ⇒ one local size.

    Groups that share content must compress IDENTICAL buffers or their
    copies silently diverge (the quantization scale is the whole-buffer
    L2, compressed.py:_compress):
    - body vs pipe-replicated rest → separate buffers (round 3);
    - with a ``model`` axis (3D, round 4), model-sharded ``mp_*`` leaves
      vs model-replicated body leaves → a third split, so the replicated
      leaves see the same scale on every model rank. Buffers concatenate
      [mp | body_rep | rest] along the last dim; worker/server errors get
      a model dim: [S, M, world, ...].
    """
    mp_n, rep_n, rest_n = _pipeline_local_sizes(params, num_stages, model)
    zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
    m = jax.tree_util.tree_map(zeros, params)
    v = jax.tree_util.tree_map(zeros, params)
    pr, cr = error_feedback_sizes(max(rest_n, 8), world)
    if model > 1:
        pm, cm = error_feedback_sizes(max(mp_n, 8), world)
        pb, cb = error_feedback_sizes(max(rep_n, 8), world)
        return OnebitAdamState(
            m=m, v=v, step=jnp.asarray(0, jnp.int32),
            worker_error=jnp.zeros((num_stages, model, world, pm + pb + pr),
                                   jnp.float32),
            server_error=jnp.zeros((num_stages, model, world, cm + cb + cr),
                                   jnp.float32),
        )
    pb, cb = error_feedback_sizes(mp_n + rep_n, world)
    return OnebitAdamState(
        m=m, v=v, step=jnp.asarray(0, jnp.int32),
        worker_error=jnp.zeros((num_stages, world, pb + pr), jnp.float32),
        server_error=jnp.zeros((num_stages, world, cb + cr), jnp.float32),
    )


def pipeline_onebit_splits(params, world, num_stages, model=1):
    """The concatenation layout of the pipeline state's error buffers:
    ``model == 1`` → ((padded_body, chunk_body), (padded_rest,
    chunk_rest)); ``model > 1`` → ((padded_mp, chunk_mp), (padded_rep,
    chunk_rep), (padded_rest, chunk_rest))."""
    mp_n, rep_n, rest_n = _pipeline_local_sizes(params, num_stages, model)
    rest = error_feedback_sizes(max(rest_n, 8), world)
    if model > 1:
        return (error_feedback_sizes(max(mp_n, 8), world),
                error_feedback_sizes(max(rep_n, 8), world), rest)
    return error_feedback_sizes(mp_n + rep_n, world), rest


def onebit_adam_update(params,
                       local_grads,
                       state: OnebitAdamState,
                       lr,
                       beta1=0.9,
                       beta2=0.999,
                       eps=1e-8,
                       weight_decay=0.0,
                       freeze_step=100,
                       axis_name="data"):
    """One 1-bit Adam step; call inside ``shard_map`` over ``axis_name``.

    ``local_grads`` are this shard's *unaveraged* gradients; the dense
    warmup branch averages them with ``pmean``, the compression branch
    folds them into the momentum and averages that with the 1-bit
    collective. Returns ``(new_params, new_state)``.
    """
    step = state.step + 1
    g_flat, _ = ravel_pytree(local_grads)
    g_flat = g_flat.astype(jnp.float32)
    m_flat, unravel = ravel_pytree(state.m)
    v_flat, _ = ravel_pytree(state.v)
    n = g_flat.shape[0]
    # Local views under shard_map: worker_error is this rank's full-length
    # row; server_error is this rank's served chunk.
    padded_n = state.worker_error.shape[-1]
    we = state.worker_error.reshape(-1)
    se = state.server_error

    def warmup(_):
        g_avg = jax.lax.pmean(g_flat, axis_name)
        m_new = beta1 * m_flat + (1.0 - beta1) * g_avg
        v_new = beta2 * v_flat + (1.0 - beta2) * jnp.square(g_avg)
        return m_new, v_new, we, se

    def compressed(_):
        m_local = beta1 * m_flat + (1.0 - beta1) * g_flat
        m_pad = jnp.zeros((padded_n,), jnp.float32).at[:n].set(m_local)
        m_avg, we_new, se_new = compressed_allreduce(
            m_pad, we, se, axis_name, n_valid=n)
        return m_avg[:n], v_flat, we_new, se_new

    m_new, v_new, we_new, se_new = jax.lax.cond(
        step <= freeze_step, warmup, compressed, None)

    p_flat, unravel_p = ravel_pytree(params)
    p32 = p_flat.astype(jnp.float32)
    update = m_new / (jnp.sqrt(v_new) + eps)
    if weight_decay != 0.0:
        update = update + weight_decay * p32
    new_p = (p32 - lr * update).astype(p_flat.dtype)

    new_state = OnebitAdamState(
        m=unravel(m_new),
        v=unravel(v_new),
        step=step,
        worker_error=we_new.reshape(state.worker_error.shape),
        server_error=se_new,
    )
    return unravel_p(new_p), new_state


class OnebitAdam:
    """API-parity wrapper mirroring the reference constructor surface
    (`runtime/fp16/onebit_adam.py:18-60`)."""

    def __init__(self, params=None, deepspeed=None, lr=1e-3,
                 freeze_step=100000, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, eps_inside_sqrt=False,
                 weight_decay=0.0, max_grad_norm=0.0, amsgrad=False,
                 cuda_aware=False):
        if amsgrad:
            raise RuntimeError("1-bit Adam does not support the AMSGrad "
                               "variant.")
        self.lr = lr
        self.freeze_step = freeze_step
        self.betas = tuple(betas)
        self.eps = eps
        self.weight_decay = weight_decay

    def init(self, params, world=1):
        return init_onebit_state(params, world)

    def update(self, params, grads, state, lr=None, beta1=None,
               axis_name="data"):
        return onebit_adam_update(
            params, grads, state,
            lr=self.lr if lr is None else lr,
            beta1=self.betas[0] if beta1 is None else beta1,
            beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay,
            freeze_step=self.freeze_step, axis_name=axis_name)
