"""Data loading.

Analog of the reference's ``DeepSpeedDataLoader`` (`runtime/dataloader.py:33`)
and ``RepeatingLoader`` (:10). Key difference: JAX is single-controller per
host, so instead of a per-rank ``DistributedSampler`` the loader yields
*global* batches on each host's process shard; the engine shards rows over
the ``data`` mesh axis when placing the batch on devices. For multi-host,
each process loads its ``process_index``-strided slice.
"""

import math

import numpy as np


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference :10).

    On each restart, advances the wrapped loader's epoch (when it supports
    ``set_epoch``) so shuffling differs across epochs — the engine path's
    analog of advancing a DistributedSampler's epoch.
    """

    def __init__(self, loader):
        self.loader = loader
        self.epoch = 0
        self.batches_served = 0
        self.samples_served = 0
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.epoch += 1
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(self.epoch)
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        self.batches_served += 1
        self.samples_served += _batch_rows(batch)
        return batch

    # -- resume (runtime/resilience auto-resume restores data position) --
    def state_dict(self):
        # samples_served is the global sample cursor: unlike the batch
        # index it survives a world-size or micro-batch change on elastic
        # resume (the same position counted in different-sized batches).
        # batches_served stays for checkpoints read by older code.
        return {"epoch": self.epoch,
                "batches_served": self.batches_served,
                "samples_served": self.samples_served}

    def load_state_dict(self, state):
        """Fast-forward to the saved position by replaying the stream from
        the start: batch order is a pure function of (seed, epoch), so
        redrawing reproduces the exact sequence — the resumed run sees
        bit-identical batches to an uninterrupted one. Replay cost is one
        collate per skipped batch (no device transfer).

        Position is the global *sample* cursor when the checkpoint has
        one (so it lands correctly after an elastic batch re-factor);
        pre-elastic checkpoints fall back to the batch index."""
        self.epoch = 0
        self.batches_served = 0
        self.samples_served = 0
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(0)
        self.data_iter = iter(self.loader)
        target_samples = state.get("samples_served")
        if target_samples is None:
            for _ in range(int(state["batches_served"])):
                next(self)
            return
        while self.samples_served < int(target_samples):
            next(self)
        if self.samples_served != int(target_samples):
            # New batch size does not divide the saved cursor: land on
            # the next batch boundary (at most one batch of overlap is
            # re-served, never silently skipped data).
            import logging
            logging.getLogger(__name__).warning(
                "dataloader resume: saved sample cursor %s is not a "
                "multiple of the current batch size; resuming at %s",
                target_samples, self.samples_served)


def _batch_rows(batch):
    """Number of rows in a collated batch (leading dim of its first
    array), for the global sample cursor."""
    first = batch
    while isinstance(first, dict):
        first = next(iter(first.values()))
    while isinstance(first, (tuple, list)):
        first = first[0]
    shape = np.shape(first)
    return int(shape[0]) if shape else 1


def _default_collate(samples):
    """Stack a list of samples (dicts of arrays, tuples, or arrays)."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples])
                for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([np.asarray(s[i]) for s in samples])
                           for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    """Batched loader over an indexable dataset with per-process sharding.

    ``batch_size`` here is the number of rows this loader emits per
    ``__next__`` — the engine asks for the *global* train batch and shards
    it over the mesh. On multi-host runs each process sees a strided subset
    of the dataset and emits its ``batch_size // process_count`` share.
    """

    def __init__(self,
                 dataset,
                 batch_size,
                 collate_fn=None,
                 shuffle=True,
                 seed=0,
                 drop_last=True,
                 process_index=None,
                 process_count=None):
        if process_index is None or process_count is None:
            try:
                import jax
                process_index = jax.process_index()
                process_count = jax.process_count()
            except Exception:
                process_index, process_count = 0, 1
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.process_index = process_index
        self.process_count = process_count
        self.epoch = 0

        n = len(dataset)
        self.num_local = n // process_count if drop_last \
            else math.ceil(n / process_count)
        self.local_batch = batch_size // process_count
        assert self.local_batch >= 1, (
            f"batch_size {batch_size} < process_count {process_count}")
        self.len = self.num_local // self.local_batch if drop_last \
            else math.ceil(self.num_local / self.local_batch)

    def set_epoch(self, epoch):
        self.epoch = epoch

    def state_dict(self):
        return {"epoch": self.epoch}

    def load_state_dict(self, state):
        self.set_epoch(int(state["epoch"]))

    def __len__(self):
        return self.len

    def __iter__(self):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        # Strided per-process shard (DistributedSampler semantics).
        local = order[self.process_index::self.process_count][:self.num_local]
        for i in range(self.len):
            idx = local[i * self.local_batch:(i + 1) * self.local_batch]
            if len(idx) == 0:
                return
            yield self.collate_fn([self.dataset[int(j)] for j in idx])
