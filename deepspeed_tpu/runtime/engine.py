"""DeepSpeedEngine: the central training wrapper, TPU-native.

Analog of the reference's ``DeepSpeedEngine`` (`runtime/engine.py:91` —
``forward``:783, ``backward``:824, ``step``:960, checkpoints:1215-1482), with
the hook-driven mutable-tensor machinery replaced by one compiled train step:

- grad accumulation   → ``lax.scan`` over microbatches inside the step
- DP gradient allreduce → GSPMD: mean loss over the data-sharded batch
- ZeRO 1/2/3          → sharding declarations (see `runtime/zero/sharding.py`)
- fp16 master weights → fp32 params cast to compute dtype inside the grad fn
- dynamic loss scale  → pure state machine + ``jnp.where`` skip (the
  data-dependent overflow skip lives *inside* jit)
- LR/momentum schedule → folded into the step as functions of the counter

The imperative ``forward``/``backward``/``step`` micro-batch API is kept as a
compatibility shim; ``train_batch`` is the fast path (one XLA program per
global batch).
"""

import collections
import contextlib
import os
import json
import signal
import socket
import time
from typing import Any, Callable, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.analysis.audit import (
    AuditError,
    AuditReport,
    audit_compiled_step,
    check_recompile,
    donated_jit,
)
from deepspeed_tpu.runtime.config import (
    ADAM_OPTIMIZER,
    DeepSpeedConfig,
    LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER,
)
from deepspeed_tpu.runtime.fp16.loss_scaler import (
    LossScaleState,
    init_loss_scale_state,
    update_loss_scale,
)
from deepspeed_tpu.runtime.lr_schedules import get_lr_scheduler, OneCycle
from deepspeed_tpu.runtime.utils import check_overflow, clip_by_global_norm, global_norm
from deepspeed_tpu.runtime.zero.sharding import (
    build_zero_shardings, constrain_tree, make_param_caster)
from deepspeed_tpu.runtime.zero.stage3 import (
    make_gather_on_use_caster, zero3_remat_policy)
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
from deepspeed_tpu.runtime.elastic import (
    CheckpointTopologyError, check_topology, current_topology,
    stream_device_put)
from deepspeed_tpu.runtime.elastic.topology import spec_to_json
from deepspeed_tpu.runtime.resilience import fault_injection
from deepspeed_tpu.runtime.resilience.checkpoint import CheckpointManager
from deepspeed_tpu.runtime.resilience.hotckpt import (
    HotCheckpointCorruptError,
    HotCheckpointStore,
)
from deepspeed_tpu.runtime.resilience.guards import (
    ACTION_ABORT, ACTION_ROLLBACK, ACTION_SKIP_STEP,
    HealthGuardAbort, StepHealthMonitor)
from deepspeed_tpu.runtime.resilience.preemption import (
    PreemptedError, PreemptionHandler)
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.ops.adam.fused_adam import adam_update, init_adam_state
from deepspeed_tpu.ops.fp8 import fp8_scope, init_state_bundle
from deepspeed_tpu.ops.lamb.fused_lamb import init_lamb_state, lamb_update
from deepspeed_tpu.parallel.collectives import record_collective_sites
from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.telemetry import (
    StepAnomalyDetector, TelemetrySession, TraceProfiler, null_span,
    set_default_session)
from deepspeed_tpu.telemetry.timers import (
    SynchronizedWallClockTimer, ThroughputTimer)
from deepspeed_tpu.utils.compat import shard_map
from deepspeed_tpu.utils.logging import log_dist, logger

MEMORY_OPT_ALLREDUCE_SIZE = 500000000


class DeviceState(NamedTuple):
    """Device-resident step state threaded through the compiled train step."""
    loss_scale: LossScaleState
    global_step: jnp.ndarray     # i32 — optimizer-step boundaries seen
    skipped_steps: jnp.ndarray   # i32 — overflow-skipped steps
    consecutive_skipped: jnp.ndarray  # i32 — current overflow-skip streak


def grad_epilogue(grads, scale, accum, fp16, clip, constrain=None,
                  vote=None, norm_reduce=None, clip_norm_reduce=None,
                  detect_nonfinite=False, nan_skip=False):
    """Shared post-gradient block for every train-step flavor: unscale and
    average over microbatches → optional sharding constraint → overflow
    check (optionally cross-shard voted) → grad norms → clipping.

    Returns ``(grads, overflow, nonfinite, grad_norm, applied_norm)``.
    ``norm_reduce`` maps a local norm to the reported one (identity for
    GSPMD steps, pmean under shard_map); ``clip_norm_reduce`` picks the
    norm the clip factor is computed from (must be rank-consistent under
    shard_map).

    ``detect_nonfinite`` forces the finiteness check on even for
    fp32/bf16 runs (the resilience NaN guard's in-jit detector — normally
    the check is compiled out when fp16 scaling is off); ``nan_skip``
    additionally folds the verdict into ``overflow`` so the existing
    overflow-skip machinery drops the poisoned update. ``nonfinite`` is
    always the raw detector verdict, independent of the skip decision."""
    denom = scale * accum
    grads = jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32) / denom, grads)
    if constrain is not None:
        grads = constrain(grads)
    if fp16 or detect_nonfinite:
        nonfinite = check_overflow(grads)
    else:
        nonfinite = jnp.asarray(False)
    if vote is not None:
        nonfinite = vote(nonfinite)
    overflow = nonfinite if (fp16 or nan_skip) else jnp.asarray(False)
    nr = norm_reduce if norm_reduce is not None else (lambda n: n)
    cnr = clip_norm_reduce if clip_norm_reduce is not None else (lambda n: n)
    local_norm = global_norm(grads)
    grad_norm = nr(local_norm)
    applied_norm = grad_norm
    if clip > 0:
        grads = clip_by_global_norm(grads, clip, norm=cnr(local_norm))
        applied_norm = nr(global_norm(grads))
    return grads, overflow, nonfinite, grad_norm, applied_norm


def loss_scale_epilogue(dstate, overflow, fp16, dynamic, scale_args):
    """Dynamic-loss-scale update + step/skip counters (reference
    stage2.py:1341-1362 overflow-skip semantics), shared by all steps."""
    if fp16 and dynamic:
        new_scale = update_loss_scale(dstate.loss_scale, overflow,
                                      **scale_args)
    else:
        new_scale = dstate.loss_scale
    overflow_i32 = overflow.astype(jnp.int32)
    return DeviceState(
        loss_scale=new_scale,
        global_step=dstate.global_step + 1,
        skipped_steps=dstate.skipped_steps + overflow_i32,
        # Streak of back-to-back skips: the host-visible signal that a
        # run is dead (always overflowing) vs. merely rescaling.
        consecutive_skipped=(dstate.consecutive_skipped + 1) * overflow_i32)


def step_metrics(loss_sum, accum, grad_norm, applied_norm, lr, scale,
                 overflow, loss_reduce=None, dstate=None, nonfinite=None):
    loss = loss_sum / accum
    if loss_reduce is not None:
        loss = loss_reduce(loss)
    out = {
        "loss": loss,
        "grad_norm": grad_norm,
        "applied_grad_norm": applied_norm,
        "lr": lr,
        "loss_scale": scale,
        "overflow": overflow,
    }
    if dstate is not None:
        # Post-update counters (pass dstate_out): overflow skips are no
        # longer silent — a dead run shows a growing streak here.
        out["skipped_steps"] = dstate.skipped_steps
        out["consecutive_skipped_steps"] = dstate.consecutive_skipped
    if nonfinite is not None:
        out["grad_nonfinite"] = nonfinite
    return out


def make_grad_accumulator(loss_fn, compute_dtype, accum, constrain=None,
                          cast_params=None, remat_policy=None,
                          fp8_plan=None):
    """Build ``accumulate(params, batch, rng, scale) -> (loss_sum, grads)``:
    scaled-loss value-and-grad over one microbatch, or a ``lax.scan`` over
    ``accum`` microbatches (batch leading dim = accum). Shared by the dense
    and the 1-bit (shard_map) train steps.

    ``constrain`` (grad pytree → grad pytree) pins the gradient layout —
    under ZeRO-2 the scan *carry* is constrained to the sharded-gradient
    layout, so the replicated full gradient never materializes across
    microbatches (the IPG-partition contract of reference stage2.py:613-738;
    constraining only after the scan would leave the carry layout to XLA's
    guess).

    ``cast_params`` overrides the default fp32→compute-dtype cast — the
    ZeRO-3 path passes the cast-then-gather transform
    (`zero/sharding.py:make_param_caster` or the explicit
    `zero/stage3.py:make_gather_on_use_caster`) so param all-gathers ride
    the wire at 16 bit.

    ``remat_policy`` wraps the microbatch forward in ``jax.checkpoint``
    with that policy — the explicit ZeRO-3 step passes
    `zero/stage3.py:zero3_remat_policy` so the gathered 16-bit params are
    dropped at the fwd/bwd boundary and the backward re-gathers them from
    the fp32 shards (remat re-executes the same gathers on the same
    inputs, so numerics are bitwise-unchanged).

    ``fp8_plan`` (an `ops/fp8.py:Fp8Plan`) turns on fp8 delayed-scaling
    matmuls: ``accumulate`` then takes a trailing ``fp8_state`` dict of
    per-site amax-history bundles and returns ``(loss_sum, grads,
    fp8_state_out)``. The microbatch forward runs under ``fp8_scope``
    and the loss is differentiated w.r.t. ``(params, fp8_state)`` — the
    state's "gradients" ARE the rolled histories (the grad-as-state-
    update trick in `ops/fp8.py`). Across an accumulation scan the
    per-micro updates combine elementwise via ``jnp.maximum``: every
    micro sees the same input histories, so the max over their slot-0
    amaxes is the step's amax and the older slots agree."""

    user_caster = cast_params
    if cast_params is None:
        def cast_params(p):
            return jax.tree_util.tree_map(
                lambda x: x.astype(compute_dtype), p)

    # A loss_fn may carry a hand-written (loss, grads) implementation that
    # cannot be expressed as jax.grad of a scalar function — the executed
    # 1F1B pipeline (pipe/pipeline.py:make_pipeline_value_and_grad_fn)
    # interleaves forward and backward ticks, which AD cannot.
    direct = getattr(loss_fn, "direct_value_and_grad", None)
    if direct is not None and user_caster is not None:
        # ADVICE r4: the direct path runs the loss_fn's own casts, so a
        # ZeRO-3 cast-then-gather caster built for it would silently fall
        # back to XLA's fp32 gather-then-cast — surface the lost
        # param-traffic halving instead of eating it.
        log_dist("cast_params is ignored on the direct value-and-grad "
                 "path: the 16-bit cast-then-gather wire does not apply; "
                 "param gathers will ride at fp32", ranks=[0])

    def forward(p, micro_batch, rng, loss_kwargs):
        if fp8_plan is None:
            return loss_fn(cast_params(p), micro_batch, rng, **loss_kwargs)
        # fp8: the differentiated argument is (params, fp8_state); the
        # scope only needs to span the forward trace — the qdq
        # custom_vjps carry everything the backward needs in residuals.
        p, f8 = p
        with fp8_scope(fp8_plan, f8):
            return loss_fn(cast_params(p), micro_batch, rng, **loss_kwargs)

    if remat_policy is not None:
        forward = jax.checkpoint(forward, policy=remat_policy)

    def micro_grads(params, micro_batch, rng, scale, loss_kwargs,
                    fp8_state=None):
        if direct is not None:
            return direct(params, micro_batch, rng, scale, **loss_kwargs)

        arg = params if fp8_state is None else (params, fp8_state)

        def scaled_loss(p):
            loss = forward(p, micro_batch, rng, loss_kwargs)
            return loss * scale, loss
        (_, loss), grads = jax.value_and_grad(
            scaled_loss, has_aux=True)(arg)
        if fp8_state is None:
            return loss, grads
        grads, f8_out = grads
        return loss, grads, f8_out

    # The explicit ZeRO-3 caster exposes its SiteRecord registration as
    # a hook to be fired out here, outside the remat/shard_map trace
    # caches — inside them the log goes quiet on an audit's retrace.
    declare_sites = getattr(user_caster, "declare_sites", None)

    def accumulate(params, batch, rng, scale, loss_kwargs=None,
                   fp8_state=None):
        if declare_sites is not None and direct is None:
            declare_sites()
        assert (fp8_state is not None) == (
            fp8_plan is not None and direct is None), \
            "fp8_state must be passed exactly when an fp8_plan is active"
        loss_kwargs = loss_kwargs or {}
        if accum == 1:
            micro = jax.tree_util.tree_map(lambda x: x[0], batch)
            if fp8_state is None:
                return micro_grads(params, micro, rng, scale, loss_kwargs)
            return micro_grads(params, micro, rng, scale, loss_kwargs,
                               fp8_state)
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        if constrain is not None:
            zeros = constrain(zeros)

        if fp8_state is not None:
            # Histories are non-negative amaxes and every micro sees the
            # same input state, so elementwise max over the per-micro
            # updates (zero-init is the identity) is the step's update.
            f8_zeros = jax.tree_util.tree_map(jnp.zeros_like, fp8_state)

            def body_fp8(carry, micro):
                g_acc, f8_acc, loss_acc, key = carry
                key, sub = jax.random.split(key)
                loss, g, f8_new = micro_grads(params, micro, sub, scale,
                                              loss_kwargs, fp8_state)
                g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                if constrain is not None:
                    g_acc = constrain(g_acc)
                f8_acc = jax.tree_util.tree_map(jnp.maximum, f8_acc,
                                                f8_new)
                return (g_acc, f8_acc, loss_acc + loss, key), None

            (grads, f8_out, loss_sum, _), _ = jax.lax.scan(
                body_fp8,
                (zeros, f8_zeros, jnp.asarray(0.0, jnp.float32), rng),
                batch)
            return loss_sum, grads, f8_out

        def body(carry, micro):
            g_acc, loss_acc, key = carry
            key, sub = jax.random.split(key)
            loss, g = micro_grads(params, micro, sub, scale, loss_kwargs)
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
            if constrain is not None:
                g_acc = constrain(g_acc)
            return (g_acc, loss_acc + loss, key), None

        (grads, loss_sum, _), _ = jax.lax.scan(
            body, (zeros, jnp.asarray(0.0, jnp.float32), rng), batch)
        return loss_sum, grads

    return accumulate


class DeepSpeedEngine:
    """Training engine around a pure ``loss_fn(params, batch, rng)``."""

    def __init__(self,
                 args=None,
                 model=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mpu=None,
                 dist_init_required=None,
                 collate_fn=None,
                 config=None,
                 config_params=None,
                 loss_fn: Optional[Callable] = None,
                 params=None,
                 param_specs=None,
                 mesh=None,
                 seed: int = 0):
        # --- resolve the model contract ---------------------------------
        if loss_fn is None and model is not None and hasattr(model, "loss_fn"):
            loss_fn = model.loss_fn
        if params is None and model_parameters is not None:
            params = model_parameters
        if params is None and model is not None and hasattr(model, "params"):
            params = model.params
        assert loss_fn is not None, (
            "deepspeed_tpu needs a pure loss_fn(params, batch, rng) — pass "
            "loss_fn= directly or a model object exposing .loss_fn")
        assert params is not None, "initial params pytree required"
        self.module = model
        self.loss_fn = loss_fn

        # --- config ------------------------------------------------------
        if config is None and config_params is not None:
            config = config_params
        if config is None and args is not None and \
                getattr(args, "deepspeed_config", None):
            config = args.deepspeed_config
        assert config is not None, "config (dict or json path) required"

        # Multi-host rendezvous first (no-op single-process): scripts
        # spawned by the launcher carry DS_TPU_* env and must join the
        # jax.distributed cluster before any device/mesh query (the
        # reference's dist.init_process_group at engine.py:135).
        from deepspeed_tpu.parallel.mesh import initialize_distributed
        try:
            initialize_distributed()
        except RuntimeError as e:
            if "before" in str(e) and "JAX" in str(e):
                raise RuntimeError(
                    "multi-process rendezvous env (DS_TPU_*) is set but "
                    "the XLA backend was already initialized — call "
                    "deepspeed_tpu.parallel.initialize_distributed() at "
                    "the top of your script, before creating any jax "
                    "array") from e
            raise
        self.mesh = mesh if mesh is not None else build_mesh(
            (config.get("mesh") if isinstance(config, dict) else None))
        self.dp_world_size = self.mesh.shape["data"]
        self.mp_world_size = self.mesh.shape["model"]
        self._config = DeepSpeedConfig(config, world_size=self.dp_world_size)
        if self._config.compilation_cache_dir:
            # before ANY engine jit (opt-state init compiles below)
            jax.config.update("jax_compilation_cache_dir",
                              self._config.compilation_cache_dir)
            try:
                # jax latches "no cache" at the process's FIRST compile
                # (param init/mesh build typically precede the engine);
                # reset so the next compile re-reads the dir.
                from jax._src import compilation_cache as _jax_cc
                _jax_cc.reset_cache()
            except Exception:  # pragma: no cover - jax internals moved
                pass
            from deepspeed_tpu.telemetry import compile_cache
            compile_cache.install()

        # --- precision policy -------------------------------------------
        if self._config.fp16_enabled:
            self.compute_dtype = jnp.float16
        elif self._config.bf16_enabled or self._config.amp_enabled:
            self.compute_dtype = jnp.bfloat16
        else:
            self.compute_dtype = jnp.float32
        self.dynamic_loss_scale = (self._config.fp16_enabled and
                                   self._config.loss_scale == 0)
        if self._config.fp16_enabled and self._config.loss_scale > 0:
            self.static_loss_scale = float(self._config.loss_scale)
        else:
            self.static_loss_scale = 1.0

        # --- counters ----------------------------------------------------
        self.micro_steps = 0
        self.global_steps = 0

        # --- optimizer / schedule ----------------------------------------
        self._configure_optimizer(optimizer)
        self._configure_lr_scheduler(lr_scheduler)

        # --- shardings & placement ---------------------------------------
        base_specs = param_specs if param_specs is not None else \
            jax.tree_util.tree_map(lambda _: PartitionSpec(), params)
        self._shardings = build_zero_shardings(
            params, base_specs, self.mesh, self.zero_optimization_stage())
        self._offload = bool(self._config.zero_enabled and
                             self._config.zero_config.cpu_offload)
        if self._config.zero_config.offload_16bit_grads and \
                not self._offload:
            log_dist("offload_16bit_grads: true has no effect without "
                     "cpu_offload: true (grads only cross the wire on the "
                     "offload path)", ranks=[0])
        if self._config.zero_config.offload_16bit_grads and \
                self._offload and self._config.fp16_enabled:
            # ADVICE r4: the 16-bit wire is bf16-gated (fp16 would flush
            # unscaled sub-6e-5 grad components) — say so instead of
            # silently transferring fp32.
            log_dist("offload_16bit_grads: true is inert under fp16 "
                     "compute (grads are unscaled on device before "
                     "transfer; fp16 would flush sub-6e-5 components). "
                     "Grads transfer at fp32 — use bf16 to get the "
                     "16-bit wire", ranks=[0])
        if self._offload:
            # ZeRO-Offload (reference stage2.py cpu_offload + csrc cpu_adam):
            # fp32 masters + moments live in host RAM inside the C++
            # DeepSpeedCPUAdam; the device holds compute-dtype params only,
            # and the compiled step produces gradients, not updates.
            assert self.optimizer_name in (ADAM_OPTIMIZER, "adamw"), (
                f"cpu_offload supports adam/adamw, got {self.optimizer_name}")
            # Offload×DP (round 5, reference stage-2 offload semantics:
            # each rank updates only its gradient partition,
            # stage2.py:1410-1423): under multi-process the compiled step
            # emits the gradient as a flat [D, chunk] array sharded over
            # the data axis, each process's host Adam updates its
            # contiguous shard of the flat master buffer, and the updated
            # params reassemble on device via an XLA all-gather riding
            # ICI — no host-side parameter exchange.
            self._offload_dp = jax.process_count() > 1
            if self._offload_dp:
                other = {k: v for k, v in self.mesh.shape.items()
                         if k != "data" and v > 1}
                assert not other, (
                    "multi-process cpu_offload supports pure data-parallel "
                    f"meshes only; non-data axes present: {other}")
            from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
            opt_params = dict(self._config.optimizer_params or {})
            self.cpu_optimizer = DeepSpeedCPUAdam(
                params,
                lr=opt_params.get("lr", self._base_lr),
                betas=self._betas,
                eps=opt_params.get("eps", 1e-8),
                weight_decay=opt_params.get("weight_decay", 0.0),
                bias_correction=opt_params.get("bias_correction", True),
                adamw_mode=opt_params.get("adam_w_mode",
                                          self.optimizer_name == "adamw"))
            chunk_mb = self._config.zero_config.offload_chunk_mb
            if not chunk_mb or float(chunk_mb) <= 0:
                raise ValueError(
                    f"offload_chunk_mb must be a positive number of MB, "
                    f"got {chunk_mb!r}")
            # Fractional MB allowed; floor at 64 KB so a tiny value can't
            # degenerate into one pool submission per element.
            self._offload_chunk_bytes = max(
                64 << 10, int(float(chunk_mb) * (1 << 20)))
            if self._offload_dp:
                D = self.mesh.shape["data"]
                self._off_D = D
                self._off_chunk = -(-self.cpu_optimizer.total // D)
            self.params = self._upload_offload_params()
            self.opt_state = None
            self.last_host_phase_s = 0.0
        else:
            self.cpu_optimizer = None
            # Copy (never alias) the caller's params: the compiled train
            # step donates the engine's buffers, and donating the caller's
            # arrays would delete them out from under the caller.
            fp32 = jax.tree_util.tree_map(
                lambda p: jnp.array(p, dtype=jnp.float32, copy=True), params)
            self.params = jax.device_put(fp32, self._shardings["param"])
            self.opt_state = jax.jit(
                self.opt_init_fn,
                out_shardings=self._opt_state_shardings())(self.params)
        self.device_state = self._init_device_state()

        # --- data --------------------------------------------------------
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(
                training_data, collate_fn=collate_fn)
        from deepspeed_tpu.runtime.dataloader import RepeatingLoader
        self._data_iter = iter(RepeatingLoader(self.training_dataloader)) \
            if self.training_dataloader is not None else None

        # --- aux ---------------------------------------------------------
        self.progressive_layer_drop = None
        if self._config.pld_enabled:
            self.progressive_layer_drop = ProgressiveLayerDrop(
                **(self._config.pld_params or {}))
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self._config.train_micro_batch_size_per_gpu *
            self._config.gradient_accumulation_steps,
            num_workers=self.dp_world_size,
            steps_per_output=self._config.steps_per_print)
        self.trace_profiler = TraceProfiler(
            **(self._config.profiling_params or {}))
        if self.trace_profiler.enabled:
            import atexit
            atexit.register(self.trace_profiler.close)

        # --- telemetry (deepspeed_tpu/telemetry) -------------------------
        # One session per engine: metrics registry + schema-versioned
        # event log + the span API train_batch wraps its host phases in.
        # Also installed as the process default (first engine wins) so
        # engine-external emitters (elastic reshard, bench.py) land in
        # the same log. metrics_history is the bounded step-event ring
        # tests and health guards read without file I/O.
        tl = self._config.telemetry
        self.telemetry = None
        self.metrics_history = collections.deque(maxlen=tl.history)
        self._batch_tokens = None
        self._anomaly_detector = None
        # Process identity stamped on run_start/step events and flight
        # dumps — the join key `ds_tpu_metrics aggregate` uses to build
        # the cross-host skew table from per-host logs.
        self._proc_meta = {"process_index": jax.process_index(),
                           "process_count": jax.process_count(),
                           "hostname": socket.gethostname()}
        if tl.enabled:
            self.telemetry = TelemetrySession.from_config(
                tl, meta={**self._proc_meta,
                          "flavor": self._telemetry_flavor(),
                          **self._forensics_extra()})
            set_default_session(self.telemetry, replace=False)
            import atexit
            atexit.register(self.telemetry.close)
            if tl.anomaly_trace_enabled:
                self._anomaly_detector = StepAnomalyDetector(
                    factor=tl.anomaly_trace_factor,
                    window=tl.anomaly_trace_window)
            self.telemetry.emit(
                "run_start",
                flavor=self._telemetry_flavor(),
                train_batch_size=self._config.train_batch_size,
                gradient_accumulation_steps=self._config
                .gradient_accumulation_steps,
                zero_stage=self.zero_optimization_stage(),
                dp_world_size=self.dp_world_size,
                mp_world_size=self.mp_world_size,
                n_devices=len(jax.devices()),
                fp16=self.fp16_enabled(),
                bf16=self.bfloat16_enabled(),
                flops_per_token=tl.flops_per_token or None,
                **self._proc_meta,
                **self._forensics_extra())
        self.summary_writer = None
        if self._config.tensorboard_enabled and jax.process_index() == 0:
            self.summary_writer = self._get_summary_writer()

        # Activation checkpointing module config (reference
        # `_configure_checkpointing`, engine.py:412). An explicit user
        # configure() beforehand wins over the engine's JSON-derived one.
        from deepspeed_tpu.runtime.activation_checkpointing import (
            checkpointing as _act_ckpt)
        if not _act_ckpt.is_configured():
            _act_ckpt.configure(mpu_=mpu, deepspeed_config=self._config)

        self._rng = jax.random.PRNGKey(seed)
        self._compiled_train_step = None
        self._compiled_eval_step = None
        self._grad_buffer = None
        self._pending_batch = None
        self._last_metrics = {}
        # Error-feedback residual state for the int8 quantized all-reduce
        # (`runtime/comm/quantized.py`); populated lazily by
        # `_make_quantized_train_step` when comm_quantization.error_feedback
        # is on. Ephemeral comm state — intentionally not checkpointed.
        self._qcomm_residuals = None

        # --- resilience (runtime/resilience) -----------------------------
        rz = self._config.resilience
        self._fault_arg = False
        self._ckpt_manager = CheckpointManager(
            save_dir=rz.save_dir,
            keep_last_n=rz.keep_last_n,
            async_save=rz.async_save,
            io_retries=rz.io_retries,
            io_retry_base_s=rz.io_retry_base_s,
            io_timeout_s=rz.io_timeout_s)
        # In-memory hot-checkpoint tier (runtime/resilience/hotckpt.py):
        # the restore ladder's first stop, ahead of any disk checkpoint.
        self._hot_store = None
        if rz.hot_enabled:
            self._hot_store = HotCheckpointStore(
                capacity=rz.hot_capacity,
                mirror_dir=rz.hot_mirror_dir,
                mirror_keep=rz.hot_mirror_keep,
                process_index=jax.process_index())
        self._health_monitor = None
        if rz.guards_enabled:
            self._health_monitor = StepHealthMonitor(
                nan_action=rz.nan_guard_action,
                spike_action=rz.loss_spike_action,
                collapse_action=rz.scale_collapse_action,
                fp16_dynamic=self.fp16_enabled() and self.dynamic_loss_scale,
                spike_window=rz.loss_spike_window,
                spike_factor=rz.loss_spike_factor,
                spike_min_history=rz.loss_spike_min_history,
                collapse_patience=rz.scale_collapse_patience,
                min_scale=self._scale_args()["min_scale"])
        self._preemption = None
        if rz.save_on_sigterm:
            self._preemption = PreemptionHandler()
            self._preemption.install()
        # Forensics (telemetry/flight.py, telemetry/watchdog.py): crash
        # hooks go in AFTER the preemption handler so a SIGTERM dumps
        # the flight record first, then chains into the checkpoint-at-
        # next-boundary latch. The watchdog daemon starts here too.
        if self.telemetry is not None:
            if self.telemetry.flight is not None:
                self.telemetry.flight.install()
            if self.telemetry.watchdog is not None:
                self.telemetry.watchdog.start()
        if self.cpu_optimizer is not None:
            self.cpu_optimizer.host_adam_retries = rz.host_adam_retries
            self.cpu_optimizer.host_adam_timeout_s = rz.io_timeout_s

        # --- compiled-program analysis (deepspeed_tpu/analysis) ----------
        an = self._config.analysis
        self.last_audit_report = None
        self._recompile_reported = 1
        if an.enabled:
            log_dist("analysis: compile-time audit enabled "
                     f"(rules={list(an.rules) if an.rules else 'all'}, "
                     f"fail_on_findings={an.fail_on_findings}, "
                     f"check_recompile={an.check_recompile})", ranks=[0])

        if self._config.dump_state:
            self._config.print("DeepSpeedEngine configuration")

        if rz.auto_resume:
            resumed = self._auto_resume()
            if resumed:
                log_dist(f"resilience: auto-resumed from {resumed} at "
                         f"step {self.global_steps}", ranks=[0])
            else:
                log_dist("resilience: auto_resume found no valid "
                         "checkpoint; starting fresh", ranks=[0])

    # ------------------------------------------------------------------
    # configuration accessors (reference engine.py:241-396)
    # ------------------------------------------------------------------
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def zero_optimization_stage(self):
        return self._config.zero_optimization_stage

    def zero_optimization(self):
        return self._config.zero_enabled

    def fp16_enabled(self):
        return self._config.fp16_enabled

    def bfloat16_enabled(self):
        return self._config.bf16_enabled

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def steps_per_print(self):
        return self._config.steps_per_print

    def wall_clock_breakdown(self):
        return self._config.wall_clock_breakdown

    def memory_breakdown(self):
        return self._config.memory_breakdown

    def sparse_gradients_enabled(self):
        return self._config.sparse_gradients_enabled

    def progressive_layer_drop_enabled(self):
        return self._config.pld_enabled

    def dump_state(self):
        return self._config.dump_state

    @property
    def config(self):
        return self._config

    @property
    def loss_scale(self):
        if self.dynamic_loss_scale:
            return float(self.device_state.loss_scale.cur_scale)
        return self.static_loss_scale

    @property
    def skipped_steps(self):
        return int(self.device_state.skipped_steps)

    # ------------------------------------------------------------------
    # setup helpers
    # ------------------------------------------------------------------
    def _configure_optimizer(self, client_optimizer):
        """Resolve (init_fn, update_fn) — the analog of
        `_configure_basic_optimizer` (engine.py:577)."""
        if client_optimizer is not None and not isinstance(client_optimizer, str):
            # Client passed one of our optimizer wrapper objects.
            self.client_optimizer = client_optimizer
            self.optimizer_name = type(client_optimizer).__name__.lower()
            if self.optimizer_name == ONEBIT_ADAM_OPTIMIZER:
                # The wrapper's init needs the data-parallel world size for
                # the error-feedback buffers, and the optimizer needs the
                # shard_map train step (fp16-path scope, not ZeRO).
                assert self.zero_optimization_stage() == 0, (
                    "OneBitAdam is not compatible with ZeRO "
                    "(reference scope: fp16 optimizer path only)")
                world = self.dp_world_size
                if getattr(self.loss_fn, "direct_value_and_grad_local",
                           None) is not None:
                    # pipeline composition needs [stages[, model], world,
                    # padded] error buffers (per-(stage[, model-rank])
                    # collective groups); route through the pipeline-aware
                    # init, not the wrapper's DP-shaped one.
                    from deepspeed_tpu.runtime.fp16.onebit_adam import (
                        init_pipeline_onebit_state)
                    stages = self.mesh.shape["pipe"]
                    msize = self.mesh.shape.get("model", 1)
                    self.opt_init_fn = lambda p: init_pipeline_onebit_state(
                        p, world, stages, msize)
                else:
                    self.opt_init_fn = lambda p: client_optimizer.init(
                        p, world=world)
            else:
                self.opt_init_fn = client_optimizer.init
            self._opt_update = lambda p, g, s, lr, beta1: \
                client_optimizer.update(p, g, s, lr=lr, beta1=beta1)
            self._base_lr = getattr(client_optimizer, "lr", 1e-3)
            self._betas = getattr(client_optimizer, "betas", (0.9, 0.999))
            return
        self.client_optimizer = None

        name = (self._config.optimizer_name or ADAM_OPTIMIZER).lower()
        opt_params = dict(self._config.optimizer_params or {})
        lr = opt_params.pop("lr", 1e-3)
        betas = tuple(opt_params.pop("betas", (0.9, 0.999)))
        eps = opt_params.pop("eps", 1e-8)
        weight_decay = opt_params.pop("weight_decay", 0.0)
        bias_correction = opt_params.pop("bias_correction", True)
        self._base_lr = lr
        self.optimizer_name = name

        if name == ONEBIT_ADAM_OPTIMIZER:
            # 1-bit Adam runs the fp16-optimizer path, not ZeRO (same scope
            # as the reference, whose OnebitAdam goes through FP16_Optimizer)
            # and needs local per-shard grads, so the train step switches to
            # shard_map over the data axis.
            assert self.zero_optimization_stage() == 0, (
                "OneBitAdam is not compatible with ZeRO "
                "(reference scope: fp16 optimizer path only)")
            from deepspeed_tpu.runtime.fp16.onebit_adam import (
                init_onebit_state, init_pipeline_onebit_state,
                onebit_adam_update)
            freeze_step = opt_params.pop("freeze_step", 100000)
            world = self.dp_world_size
            if getattr(self.loss_fn, "direct_value_and_grad_local",
                       None) is not None:
                # pipeline x 1-bit composition: error buffers per
                # (stage[, model-rank], data-rank) over the device-local
                # flat size
                stages = self.mesh.shape["pipe"]
                msize = self.mesh.shape.get("model", 1)
                self.opt_init_fn = lambda p: init_pipeline_onebit_state(
                    p, world, stages, msize)
            else:
                self.opt_init_fn = lambda p: init_onebit_state(p, world)
            self._opt_update = lambda p, g, s, lr_, beta1: onebit_adam_update(
                p, g, s, lr=lr_, beta1=beta1, beta2=betas[1], eps=eps,
                weight_decay=weight_decay, freeze_step=freeze_step,
                axis_name="data")
        elif name in (ADAM_OPTIMIZER, "adamw"):
            adam_w_mode = opt_params.pop("adam_w_mode", name == "adamw")
            self.opt_init_fn = init_adam_state
            # "pallas": true routes the leaf update through the explicit
            # one-pass Pallas kernel (multi_tensor_adam.cu analog,
            # ops/pallas/fused_adam.py) — TPU only, and only with
            # unsharded optimizer state: pallas_call has no GSPMD
            # partitioning rule, so under ZeRO it would force per-step
            # all-gathers of exactly the state ZeRO shards.
            want_pallas = bool(opt_params.pop("pallas", False))
            use_pallas = want_pallas and \
                jax.devices()[0].platform == "tpu" and \
                self.zero_optimization_stage() == 0
            if want_pallas and not use_pallas:
                log_dist("optimizer 'pallas': true ignored (needs TPU and "
                         "ZeRO stage 0); using the XLA fused update",
                         ranks=[0])
            if use_pallas:
                from deepspeed_tpu.ops.pallas import (
                    pallas_adam_update)
                self._opt_update = \
                    lambda p, g, s, lr_, beta1: pallas_adam_update(
                        p, g, s, lr=lr_, beta1=beta1, beta2=betas[1],
                        eps=eps, weight_decay=weight_decay,
                        adam_w_mode=adam_w_mode,
                        bias_correction=bias_correction)
            else:
                self._opt_update = lambda p, g, s, lr_, beta1: adam_update(
                    p, g, s, lr=lr_, beta1=beta1, beta2=betas[1], eps=eps,
                    weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                    bias_correction=bias_correction)
        elif name == LAMB_OPTIMIZER:
            max_coeff = opt_params.pop("max_coeff", 10.0)
            min_coeff = opt_params.pop("min_coeff", 0.01)
            self.opt_init_fn = init_lamb_state
            self._opt_update = lambda p, g, s, lr_, beta1: lamb_update(
                p, g, s, lr=lr_, beta1=beta1, beta2=betas[1], eps=eps,
                weight_decay=weight_decay, bias_correction=bias_correction,
                max_coeff=max_coeff, min_coeff=min_coeff)
        else:
            raise ValueError(f"unknown optimizer {name!r}; supported: adam, "
                             f"adamw, lamb, onebitadam")
        self._betas = betas

    def _configure_lr_scheduler(self, client_scheduler):
        """Schedule resolution (reference engine.py:398-444)."""
        self.lr_scheduler = None
        if client_scheduler is not None:
            self.lr_scheduler = client_scheduler
        elif self._config.scheduler_name is not None:
            self.lr_scheduler = get_lr_scheduler(self._config.scheduler_name,
                                                 self._config.scheduler_params or {})
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "lr_at"):
            # Our schedules fold into the compiled step (device-resident).
            self._lr_fn = self.lr_scheduler.lr_at
            self._lr_foldable = True
        elif self.lr_scheduler is not None and \
                hasattr(self.lr_scheduler, "get_lr"):
            # Foreign scheduler: read its lr host-side every step and feed it
            # into the compiled step as a scalar argument.
            self._lr_fn = None
            self._lr_foldable = False
            logger.info("client lr scheduler without lr_at(): lr will be "
                        "read host-side each step")
        else:
            base = self._base_lr
            self._lr_fn = lambda step: jnp.asarray(base, jnp.float32)
            self._lr_foldable = True
        if isinstance(self.lr_scheduler, OneCycle) and \
                self.lr_scheduler.cycle_momentum:
            self._mom_fn = self.lr_scheduler.mom_at
        else:
            beta1 = getattr(self, "_betas", (0.9, 0.999))[0]
            self._mom_fn = lambda step: jnp.asarray(beta1, jnp.float32)
        # Elastic batch re-factor may land on an inexact global batch; the
        # configured lr_scaling rule compensates by scaling the whole
        # schedule (exact factorizations leave scale == 1.0).
        self._elastic_lr_scale = float(
            getattr(self._config, "elastic_lr_scale", 1.0) or 1.0)
        if self._elastic_lr_scale != 1.0 and self._lr_foldable:
            inner, scale = self._lr_fn, self._elastic_lr_scale
            self._lr_fn = lambda step: inner(step) * jnp.float32(scale)

    def _opt_state_shardings(self):
        """Shardings for the optimizer-state pytree: the m/v moment trees
        follow the (possibly ZeRO-sharded) opt layout; the step counter
        replicates. AdamState and LambState share the (m, v, step) shape;
        OnebitAdamState adds data-sharded error-feedback residuals."""
        opt = self._shardings["opt"]
        rep = NamedSharding(self.mesh, PartitionSpec())
        sample = jax.eval_shape(self.opt_init_fn, self.params)
        from deepspeed_tpu.runtime.fp16.onebit_adam import OnebitAdamState
        if isinstance(sample, OnebitAdamState):
            if sample.worker_error.ndim == 4:
                # pipeline x model x 1-bit (three-way buffer split):
                # [stages, model, data_world, padded_local]. Latent until
                # data > stages: the 2-D default spec below sharded dim 0
                # over "data", which only divided by accident at data=2.
                err = NamedSharding(
                    self.mesh, PartitionSpec("pipe", "model", "data", None))
                return OnebitAdamState(m=opt, v=opt, step=rep,
                                       worker_error=err, server_error=err)
            if sample.worker_error.ndim == 3:
                # pipeline x 1-bit: [stages, data_world, padded_local]
                err = NamedSharding(self.mesh,
                                    PartitionSpec("pipe", "data", None))
                return OnebitAdamState(m=opt, v=opt, step=rep,
                                       worker_error=err, server_error=err)
            return OnebitAdamState(
                m=opt, v=opt, step=rep,
                worker_error=NamedSharding(
                    self.mesh, PartitionSpec("data", None)),
                server_error=NamedSharding(self.mesh, PartitionSpec("data")))
        return type(sample)(m=opt, v=opt, step=rep)

    def _current_host_lr(self):
        """Host-side lr for schedulers the compiled step can't fold."""
        if self._lr_foldable:
            return 0.0  # unused: lr comes from the folded schedule
        lrs = self.lr_scheduler.get_lr()
        lr = float(lrs[0] if isinstance(lrs, (list, tuple)) else lrs)
        return lr * self._elastic_lr_scale

    def _init_device_state(self):
        rep = NamedSharding(self.mesh, PartitionSpec())
        init_scale = float(self._config.initial_dynamic_scale) \
            if self.dynamic_loss_scale else self.static_loss_scale
        delayed_shift = 1
        if self._config.dynamic_loss_scale_args:
            delayed_shift = self._config.dynamic_loss_scale_args.get(
                "delayed_shift", 1)
        state = DeviceState(
            loss_scale=init_loss_scale_state(init_scale, delayed_shift),
            global_step=jnp.asarray(0, jnp.int32),
            skipped_steps=jnp.asarray(0, jnp.int32),
            consecutive_skipped=jnp.asarray(0, jnp.int32))
        return jax.device_put(state, rep)

    def _get_summary_writer(self):
        try:
            from torch.utils.tensorboard import SummaryWriter
        except Exception:
            logger.warning("tensorboard unavailable; disabling")
            return None
        base = os.environ.get("DLWS_JOB_ID", "local")
        log_dir = os.path.join(self._config.tensorboard_output_path or
                               os.path.join(".", "runs"), base,
                               self._config.tensorboard_job_name)
        os.makedirs(log_dir, exist_ok=True)
        return SummaryWriter(log_dir=log_dir)

    def deepspeed_io(self, dataset, batch_size=None, route=None,
                     collate_fn=None, num_local_io_workers=None,
                     data_sampler=None):
        """Build the DP-sharded loader (reference engine.py:706). The loader
        yields *global* batches of ``train_batch_size`` rows; the engine
        shards them over the data axis when feeding the compiled step."""
        if batch_size is None:
            batch_size = self._config.train_batch_size
        return DeepSpeedDataLoader(dataset,
                                   batch_size=batch_size,
                                   collate_fn=collate_fn,
                                   drop_last=True)

    # ------------------------------------------------------------------
    # the compiled train step
    # ------------------------------------------------------------------
    def _scale_args(self):
        args = dict(scale_factor=2.0, scale_window=1000, min_scale=1.0,
                    delayed_shift=1, consecutive_hysteresis=False)
        if self._config.dynamic_loss_scale_args:
            a = self._config.dynamic_loss_scale_args
            args.update(scale_window=a.get("scale_window", 1000),
                        min_scale=a.get("min_scale", 1.0),
                        delayed_shift=a.get("delayed_shift", 1))
        return args

    def _engine_accum_steps(self):
        """Microbatch count the compiled step scans over. PipelineEngine
        overrides to 1: its microbatching happens inside the pipeline."""
        return self._config.gradient_accumulation_steps

    def _pld_theta_fn(self):
        """Progressive-layer-drop theta(t) as a pure function of the device
        step counter, folded into the compiled step. The reference advances
        theta host-side and injects it into model kwargs every forward
        (engine.py:791-792, progressive_layer_drop.py:5); here the same
        schedule evaluates inside jit so no per-step recompile happens."""
        if not self._config.pld_enabled:
            return None
        p = self._config.pld_params or {}
        theta_bar = float(p.get("theta", 0.5))
        gamma = float(p.get("gamma", 0.001))

        def theta_fn(step):
            return (1.0 - theta_bar) * jnp.exp(
                -gamma * step.astype(jnp.float32)) + theta_bar

        return theta_fn

    def _make_train_step(self):
        if self.optimizer_name == ONEBIT_ADAM_OPTIMIZER:
            if getattr(self.loss_fn, "direct_value_and_grad_local",
                       None) is not None:
                return self._make_pipeline_onebit_train_step()
            return self._make_onebit_train_step()
        if self.sparse_gradients_enabled():
            return self._make_sparse_grad_train_step()
        if self._config.comm_quantization.enabled:
            return self._make_quantized_train_step()
        accum = self._engine_accum_steps()
        compute_dtype = self.compute_dtype
        fp16 = self._config.fp16_enabled
        clip = float(self._config.gradient_clipping or 0.0)
        prescale = self._config.prescale_gradients
        predivide = float(self._config.gradient_predivide_factor or 1.0)
        lr_fn = self._lr_fn
        mom_fn = self._mom_fn
        opt_update = self._opt_update
        loss_fn = self.loss_fn
        grad_shardings = self._shardings["grad"] if \
            self.zero_optimization_stage() >= 2 else None
        param_shardings = self._shardings["param"]
        opt_shardings = self._shardings["opt"]
        scale_args = self._scale_args()
        dynamic = self.dynamic_loss_scale
        static_scale = self.static_loss_scale
        grad_constrain = (lambda g: constrain_tree(g, grad_shardings)) \
            if grad_shardings is not None else None
        # ZeRO-3: per-use param gathers ride the wire at compute dtype
        # (cast-then-gather, exact) — the analog of the reference
        # gathering updated fp16 (not fp32 master) params at stage 1
        # (stage1.py:692). Default is the explicit gather-on-use schedule
        # (`zero/stage3.py`): dep-chained per-leaf rings + a remat policy
        # that re-gathers in the backward instead of saving the gathered
        # copies. `gather_on_use: false` keeps the legacy spec-sharded
        # caster (`zero/sharding.py:make_param_caster`), where gather
        # placement is XLA's — the bench A/B baseline.
        fp8_cfg = self._config.fp8
        fp8_plan = fp8_cfg.plan()
        if fp8_plan is not None and \
                getattr(loss_fn, "direct_value_and_grad", None) is not None:
            # The executed pipeline threads fp8 itself (current scaling,
            # pipe/pipeline.py) — the stateful delayed-scaling path only
            # applies to AD-differentiable loss_fns.
            fp8_plan = None
        caster = None
        remat_policy = None
        self._zero3_plan = None
        if self.zero_optimization_stage() >= 3 and \
                compute_dtype != jnp.float32:
            zc = self._config.zero_config
            if zc.gather_on_use:
                caster, plan = make_gather_on_use_caster(
                    self.params, param_shardings, self.mesh, compute_dtype,
                    chunks=int(zc.gather_chunks or 1),
                    prefetch=bool(zc.prefetch),
                    bidirectional=bool(zc.bidirectional),
                    wire_dtype=fp8_cfg.active_wire_dtype(),
                    wire_chunk=int(fp8_cfg.wire_chunk_size))
                if caster is not None:
                    self._zero3_plan = plan
                    remat_policy = zero3_remat_policy()
            else:
                caster = make_param_caster(self.params, param_shardings,
                                           self.mesh, compute_dtype)
        accumulate = make_grad_accumulator(loss_fn, compute_dtype, accum,
                                           constrain=grad_constrain,
                                           cast_params=caster,
                                           remat_policy=remat_policy,
                                           fp8_plan=fp8_plan)
        pld_fn = self._pld_theta_fn()
        detect, nan_skip, fault_on = self._nan_guard_flags()
        self._fault_arg = fault_on

        def train_step(params, opt_state, dstate, batch, rng, lr_in,
                       fp8_state=None, grad_fault=None):
            scale = dstate.loss_scale.cur_scale if (fp16 and dynamic) \
                else jnp.asarray(static_scale, jnp.float32)
            loss_kw = {"pld_theta": pld_fn(dstate.global_step)} \
                if pld_fn is not None else None
            if fp8_state is None:
                loss_sum, grads = accumulate(params, batch, rng, scale,
                                             loss_kw)
                f8_new = None
            else:
                loss_sum, grads, f8_new = accumulate(
                    params, batch, rng, scale, loss_kw, fp8_state)
            if fault_on:
                grads = jax.tree_util.tree_map(lambda g: g * grad_fault,
                                               grads)

            # Unscale + average over microbatches. The reference's
            # prescale_gradients / gradient_predivide_factor knobs
            # (allreduce_bucket pre/post scaling, engine.py:1082) exist to
            # keep fp16 reductions in range; here the cross-replica mean is
            # computed by XLA in fp32, so they are accepted for config
            # compatibility but are intentionally no-ops.
            grads, overflow, nonfinite, grad_norm, applied_norm = \
                grad_epilogue(grads, scale, accum, fp16, clip,
                              constrain=grad_constrain,
                              detect_nonfinite=detect, nan_skip=nan_skip)

            lr = lr_fn(dstate.global_step) if lr_fn is not None else lr_in
            beta1 = mom_fn(dstate.global_step)
            new_params, new_opt = opt_update(params, grads, opt_state, lr, beta1)

            # Overflow → skip the update (reference stage2.py:1341-1362).
            def select(old, new):
                return jax.tree_util.tree_map(
                    lambda o, n: jnp.where(overflow, o, n), old, new)
            params_out = constrain_tree(select(params, new_params),
                                        param_shardings)
            opt_out = type(opt_state)(
                m=constrain_tree(select(opt_state.m, new_opt.m), opt_shardings),
                v=constrain_tree(select(opt_state.v, new_opt.v), opt_shardings),
                step=jnp.where(overflow, opt_state.step, new_opt.step))

            dstate_out = loss_scale_epilogue(dstate, overflow, fp16, dynamic,
                                             scale_args)
            metrics = step_metrics(loss_sum, accum, grad_norm, applied_norm,
                                   lr, scale, overflow, dstate=dstate_out,
                                   nonfinite=nonfinite)
            if fp8_state is not None:
                # Overflowed steps keep the OLD amax histories: an
                # inf/nan cotangent amax would otherwise poison the
                # delayed scales for the next amax_history_len steps.
                f8_out = select(fp8_state, f8_new)
                return params_out, opt_out, dstate_out, metrics, f8_out
            return params_out, opt_out, dstate_out, metrics

        # Inputs arrive pre-placed (device_put with committed shardings);
        # outputs are pinned by the constrain_tree calls above, so plain jit
        # with donation suffices.
        if fp8_plan is None:
            def train_step_plain(params, opt_state, dstate, batch, rng,
                                 lr_in, grad_fault=None):
                return train_step(params, opt_state, dstate, batch, rng,
                                  lr_in, None, grad_fault)
            return donated_jit(train_step_plain, (0, 1, 2))

        # fp8: the amax-history state threads through the step exactly
        # like the 1-bit error-feedback residuals — a trailing donated
        # argument the host-side wrapper persists on the engine between
        # calls. Discovery (allocating the per-site bundles) is lazy on
        # the first batch.
        self._fp8_state = getattr(self, "_fp8_state", None)
        inner = donated_jit(train_step, (0, 1, 2, 6))
        engine = self

        def compiled(params, opt_state, dstate, batch, rng, lr_in, *fault):
            state = engine._ensure_fp8_state(batch, rng)
            (params, opt_state, dstate, metrics,
             engine._fp8_state) = inner(params, opt_state, dstate, batch,
                                        rng, lr_in, state, *fault)
            return params, opt_state, dstate, metrics

        compiled.inner = inner
        compiled.fp8 = True
        return compiled

    def _ensure_fp8_state(self, batch, rng):
        """Allocate the per-site fp8 amax-history bundles on first use.

        ``jax.eval_shape`` traces the loss once under a discovery-mode
        :func:`fp8_scope` — each :func:`fp8_dot_general` call records its
        ``"<site>:<idx>"`` key (per-site trace-order index) instead of
        consuming state — then one zero bundle is keyed per recorded
        site. Zero histories bootstrap to scale 1, so the first step is
        plain qdq at unit scale and the delayed scales warm up over the
        next ``amax_history_len`` steps."""
        if self._fp8_state is not None:
            return self._fp8_state
        plan = self._config.fp8.plan()
        compute_dtype = self.compute_dtype
        loss_fn = self.loss_fn
        kw = {}
        if self._config.pld_enabled:
            kw["pld_theta"] = jnp.asarray(1.0, jnp.float32)
        keys = []

        def probe(p, b, r):
            with fp8_scope(plan, None, keys):
                return loss_fn(jax.tree_util.tree_map(
                    lambda x: x.astype(compute_dtype), p), b, r, **kw)

        micro = jax.tree_util.tree_map(lambda x: x[0], batch)
        jax.eval_shape(probe, self.params, micro, rng)
        # Committed-replicated placement: the step's state OUTPUTS come
        # back committed, so an uncommitted zero-init would make the
        # second call a recompile (sharding mismatch on the donated arg).
        self._fp8_state = jax.device_put(
            {k: init_state_bundle(plan.amax_history_len) for k in keys},
            jax.sharding.NamedSharding(self.mesh,
                                       jax.sharding.PartitionSpec()))
        log_dist(f"fp8: delayed scaling active over {len(keys)} dot "
                 f"site(s)", ranks=[0])
        return self._fp8_state

    def _nan_guard_flags(self):
        """(detect_nonfinite, nan_skip, fault_on) for the step factories:
        whether the in-jit finiteness detector is forced on, whether its
        verdict skips the update, and whether the compiled step takes the
        fault-injection ``grad_fault`` multiplier argument."""
        rz = self._config.resilience
        detect = rz.nan_guard_action is not None
        nan_skip = rz.nan_guard_action == ACTION_SKIP_STEP
        return detect, nan_skip, bool(rz.fault_injection)

    # ------------------------------------------------------------------
    # resilience: preemption + guard actions
    # ------------------------------------------------------------------
    def _check_preemption(self):
        """Step-boundary preemption point (called at the top of
        ``train_batch``). The fault harness delivers a *real* SIGTERM to
        this process so the production signal path is what gets tested;
        the handler only latches a flag, and the save + clean exit happen
        here, where engine state is consistent."""
        rz = self._config.resilience
        if rz.fault_injection and \
                fault_injection.preemption_due(self.global_steps):
            if self._preemption is not None:
                os.kill(os.getpid(), signal.SIGTERM)
            else:
                # No handler installed (save_on_sigterm off): preempt
                # directly rather than let default SIGTERM kill the
                # process mid-test.
                self._preempt_now()
        if self._preemption is not None and self._preemption.preempted:
            self._preempt_now()

    def _preempt_now(self):
        rz = self._config.resilience
        path = None
        if rz.save_dir:
            tag = f"global_step{self.global_steps}"
            self.save_checkpoint(rz.save_dir, tag=tag)
            self._ckpt_manager.wait()   # the exit must not race the write
            path = self._ckpt_manager.ckpt_path(rz.save_dir, tag)
        if self.telemetry is not None:
            self.telemetry.emit("preemption", step=self.global_steps,
                                path=str(path) if path else None)
        raise PreemptedError(
            f"preempted at step {self.global_steps}" +
            (f"; checkpoint saved to {path}" if path
             else "; no resilience.save_dir configured — nothing saved"),
            checkpoint_path=path)

    def _apply_guard_trip(self, trip):
        """Execute one GuardTrip's configured action. ``warn`` and
        ``skip_step`` need no host action (the monitor already logged;
        skip happened inside the compiled step). ``rollback`` reloads the
        newest valid checkpoint, escalating to abort when there is
        nothing to roll back to. An abort dumps the flight record first —
        the aborted run's black box must out-survive the raise."""
        if trip.action == ACTION_ROLLBACK:
            rz = self._config.resilience
            path = None
            # The hot RAM tier serves in-process rollbacks in seconds —
            # no disk read, no replay of the disk save interval. A
            # corrupt/mismatched snapshot falls through to disk.
            if self._hot_store is not None:
                t0 = time.perf_counter()
                try:
                    got = self._hot_store.restore()
                except HotCheckpointCorruptError as e:
                    logger.warning("rollback: hot RAM snapshot rejected: "
                                   "%s", e)
                    got = None
                if got is not None and self._install_hot_restore(
                        got, "hot_ram"):
                    path = "<hot_ram>"
                    self._emit_recovery("hot_ram", "<ram>", t0)
            if path is None:
                path, _ = self.load_checkpoint(rz.save_dir)
            if path is None:
                self._dump_flight(f"guard_abort:{trip.guard}",
                                  extra={"guard_trip": trip.as_event()})
                raise HealthGuardAbort(trip)
            log_dist(f"health guard '{trip.guard}' rolled back to {path} "
                     f"(step {self.global_steps})", ranks=[0])
        elif trip.action == ACTION_ABORT:
            self._dump_flight(f"guard_abort:{trip.guard}",
                              extra={"guard_trip": trip.as_event()})
            raise HealthGuardAbort(trip)

    def _dump_flight(self, reason, extra=None):
        """Dump the flight record if the recorder is configured (no-op
        otherwise); never raises."""
        flight = self.telemetry.flight if self.telemetry is not None \
            else None
        if flight is not None:
            return flight.dump(reason, extra=extra)
        return None

    def _forensics_extra(self):
        """Extra run facts stamped on run_start events and flight-dump
        meta. Subclasses (the pipeline engine) add their topology."""
        return {}

    def _arm_anomaly_trace(self, reason):
        """Anomaly-triggered trace capture: arm the TraceProfiler for the
        next ``capture_steps`` steps (no-op when anomaly_trace is off, a
        window is already active, or no trace dir is resolvable)."""
        if self._anomaly_detector is None or self.telemetry is None:
            return
        tl = self._config.telemetry
        trace_dir = self.trace_profiler.trace_dir
        if trace_dir is None and tl.crash_dump_dir:
            trace_dir = os.path.join(tl.crash_dump_dir, "anomaly_traces")
        if not self.trace_profiler.arm(
                self.global_steps, tl.anomaly_trace_capture_steps,
                trace_dir=trace_dir, reason=reason):
            return
        self.telemetry.emit(
            "anomaly", step=self.global_steps, reason=reason,
            capture_steps=tl.anomaly_trace_capture_steps,
            trace_dir=self.trace_profiler.trace_dir)

    def _make_quantized_train_step(self):
        """Compiled step with the int8 chunk-scaled gradient all-reduce
        (`runtime/comm/quantized.py`) in place of the fp32 GSPMD mean.

        Hybrid structure: gradient compute + quantized exchange run inside
        ``shard_map`` over the ``data`` axis (each rank sees local grads,
        exactly like the 1-bit path), but the epilogue and optimizer
        update run OUTSIDE, in GSPMD — so the ZeRO-1/2 sharded master
        update (and its param-refresh all-gather) composes unchanged, and
        the wire carries int8 grads + fp32 param refresh only."""
        from deepspeed_tpu.runtime.comm.quantized import (
            init_residuals, quantized_allreduce_tree)

        cq = self._config.comm_quantization
        for ax, size in self.mesh.shape.items():
            assert ax == "data" or size == 1, (
                f"comm_quantization supports pure data parallelism; mesh "
                f"axis {ax!r} has size {size}")
        assert getattr(self.loss_fn, "direct_value_and_grad", None) is None \
            and getattr(self.loss_fn, "direct_value_and_grad_local",
                        None) is None, (
            "comm_quantization needs jax.grad-able loss_fn (the pipeline's "
            "direct value-and-grad runs its own data-plane reduction)")

        accum = self._engine_accum_steps()
        compute_dtype = self.compute_dtype
        fp16 = self._config.fp16_enabled
        clip = float(self._config.gradient_clipping or 0.0)
        lr_fn = self._lr_fn
        mom_fn = self._mom_fn
        opt_update = self._opt_update
        loss_fn = self.loss_fn
        scale_args = self._scale_args()
        dynamic = self.dynamic_loss_scale
        static_scale = self.static_loss_scale
        chunk_size = int(cq.chunk_size)
        bucket_bytes = int(cq.bucket_mb) * 1024 * 1024
        ef = bool(cq.error_feedback)
        world = self.dp_world_size
        grad_shardings = self._shardings["grad"] if \
            self.zero_optimization_stage() >= 2 else None
        param_shardings = self._shardings["param"]
        opt_shardings = self._shardings["opt"]
        grad_constrain = (lambda g: constrain_tree(g, grad_shardings)) \
            if grad_shardings is not None else None
        accumulate = make_grad_accumulator(loss_fn, compute_dtype, accum)
        pld_fn = self._pld_theta_fn()
        detect, nan_skip, fault_on = self._nan_guard_flags()
        if fault_on:
            log_dist("fault_injection: the quantized step does not take "
                     "the grad_fault argument; NaN-grad injection is inert "
                     "on this path", ranks=[0])

        if ef and self._qcomm_residuals is None:
            res = init_residuals(self.params, world, bucket_bytes,
                                 chunk_size)
            row = NamedSharding(self.mesh, PartitionSpec("data", None))
            self._qcomm_residuals = jax.device_put(res, jax.tree_util.
                                                   tree_map(lambda _: row,
                                                            res))
        n_buckets = len(self._qcomm_residuals["worker"]) if ef else 0

        def sync_local(params, dstate, batch, rng, residuals):
            """shard_map body: local grads → unscale → overflow vote →
            bucketed int8 exchange. Returns replicated (loss, grads,
            overflow) + this rank's new residual rows."""
            scale = dstate.loss_scale.cur_scale if (fp16 and dynamic) \
                else jnp.asarray(static_scale, jnp.float32)
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            loss_kw = {"pld_theta": pld_fn(dstate.global_step)} \
                if pld_fn is not None else None
            loss_sum, grads = accumulate(params, batch, rng, scale, loss_kw)

            # Unscale BEFORE the exchange (the GSPMD path unscales after
            # its allreduce): absmax quantization scales must be computed
            # on finite values, and EF residuals must not depend on the
            # running loss scale.
            denom = scale * accum
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) / denom, grads)
            if fp16:
                # Overflow is voted on LOCAL grads pre-quantization — an
                # inf/nan absmax poisons the int8 encoding (inf/inf = nan),
                # so overflowed steps ship zeros and are skipped anyway.
                overflow = jax.lax.pmax(
                    check_overflow(grads).astype(jnp.int32), "data") > 0
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(overflow, 0.0, g), grads)
            else:
                overflow = jnp.asarray(False)

            r = None
            if ef:
                r = {"worker": [w[0] for w in residuals["worker"]],
                     "server": [s[0] for s in residuals["server"]]}
            avg, new_r = quantized_allreduce_tree(
                grads, "data", chunk_size=chunk_size,
                bucket_bytes=bucket_bytes, residuals=r)
            loss_sum = jax.lax.pmean(loss_sum, "data")
            res_out = None
            if ef:
                res_out = {"worker": [w[None] for w in new_r["worker"]],
                           "server": [s[None] for s in new_r["server"]]}
            return loss_sum, avg, overflow, res_out

        P = PartitionSpec
        rep = P()
        param_specs = jax.tree_util.tree_map(lambda _: rep, self.params)
        dstate_specs = jax.tree_util.tree_map(lambda _: rep,
                                              self.device_state)
        grad_specs = param_specs
        res_specs = {"worker": [P("data", None)] * n_buckets,
                     "server": [P("data", None)] * n_buckets} if ef else rep
        res_out_specs = res_specs if ef else rep
        synced = shard_map(
            sync_local, mesh=self.mesh,
            in_specs=(param_specs, dstate_specs, P(None, "data"), rep,
                      res_specs),
            out_specs=(rep, grad_specs, rep, res_out_specs),
            check_vma=False)

        def train_step(params, opt_state, dstate, batch, rng, lr_in,
                       residuals):
            loss_sum, grads, voted, new_res = synced(params, dstate, batch,
                                                     rng, residuals)
            # GSPMD epilogue on the replicated, already-averaged gradient:
            # scale/accum are 1 (the shard_map body unscaled), the vote ORs
            # in the pre-quantization cross-rank overflow.
            grads, overflow, nonfinite, grad_norm, applied_norm = \
                grad_epilogue(
                    grads, jnp.asarray(1.0, jnp.float32), 1, fp16, clip,
                    constrain=grad_constrain, vote=lambda o: o | voted,
                    detect_nonfinite=detect, nan_skip=nan_skip)

            lr = lr_fn(dstate.global_step) if lr_fn is not None else lr_in
            beta1 = mom_fn(dstate.global_step)
            new_params, new_opt = opt_update(params, grads, opt_state, lr,
                                             beta1)

            def select(old, new):
                return jax.tree_util.tree_map(
                    lambda o, n: jnp.where(overflow, o, n), old, new)
            params_out = constrain_tree(select(params, new_params),
                                        param_shardings)
            opt_out = type(opt_state)(
                m=constrain_tree(select(opt_state.m, new_opt.m),
                                 opt_shardings),
                v=constrain_tree(select(opt_state.v, new_opt.v),
                                 opt_shardings),
                step=jnp.where(overflow, opt_state.step, new_opt.step))

            dstate_out = loss_scale_epilogue(dstate, overflow, fp16,
                                             dynamic, scale_args)
            scale = dstate.loss_scale.cur_scale if (fp16 and dynamic) \
                else jnp.asarray(static_scale, jnp.float32)
            metrics = step_metrics(loss_sum, accum, grad_norm, applied_norm,
                                   lr, scale, overflow, dstate=dstate_out,
                                   nonfinite=nonfinite)
            return params_out, opt_out, dstate_out, metrics, new_res

        if not ef:
            # Signature-compatible with the dense step: residuals pinned
            # to None so jit sees the same 6 logical inputs.
            def train_step_no_res(params, opt_state, dstate, batch, rng,
                                  lr_in):
                out = train_step(params, opt_state, dstate, batch, rng,
                                 lr_in, None)
                return out[0], out[1], out[2], out[3]
            return donated_jit(train_step_no_res, (0, 1, 2))

        inner = donated_jit(train_step, (0, 1, 2, 6))
        engine = self

        def compiled(params, opt_state, dstate, batch, rng, lr_in):
            params, opt_state, dstate, metrics, engine._qcomm_residuals = \
                inner(params, opt_state, dstate, batch, rng, lr_in,
                      engine._qcomm_residuals)
            return params, opt_state, dstate, metrics

        compiled.inner = inner
        return compiled

    def _upload_offload_params(self):
        """Device copy of the host fp32 masters at compute dtype (init /
        checkpoint-load path; the per-step bf16 upload is chunked inside
        ``_train_batch_offload``'s ``on_chunk`` copy-back instead). Under
        bf16 the conversion runs in the fused C++ kernel on one flat
        buffer (the reference's fused fp16 copy-back,
        csrc/adam/cpu_adam.cpp)."""
        opt = self.cpu_optimizer
        if self.compute_dtype == jnp.bfloat16:
            flat = opt.params_bf16_flat()
            leaves = [flat[off:off + size].reshape(shape)
                      for off, size, shape in zip(opt.offsets, opt.sizes,
                                                  opt.shapes)]
            tree = jax.tree_util.tree_unflatten(opt.treedef, leaves)
        else:
            tree = jax.tree_util.tree_map(
                lambda v: v if self.compute_dtype == jnp.float32
                else v.astype(self.compute_dtype), opt.params())
        return jax.device_put(tree, self._shardings["param"])

    def _make_offload_grad_step(self):
        """Compiled gradient-only step for ZeRO-Offload: loss/grads/
        overflow/clip/loss-scale on device, the optimizer update on the
        host C++ Adam (reference stage2.py:1410-1423)."""
        accum = self._engine_accum_steps()
        fp16 = self._config.fp16_enabled
        clip = float(self._config.gradient_clipping or 0.0)
        lr_fn = self._lr_fn
        mom_fn = self._mom_fn
        loss_fn = self.loss_fn
        scale_args = self._scale_args()
        dynamic = self.dynamic_loss_scale
        static_scale = self.static_loss_scale
        compute_dtype = self.compute_dtype
        # bf16 only: it shares fp32's exponent range, so casting the
        # UNSCALED gradient is safe. fp16 would flush components under
        # ~6e-5 to zero/subnormal — the reference avoids this by moving
        # still-scaled fp16 grads (stage2.py:793); our epilogue unscales
        # on device, so fp16 transfer would defeat loss scaling.
        grads_16bit = (self._config.zero_config.offload_16bit_grads and
                       compute_dtype == jnp.bfloat16)
        accumulate = make_grad_accumulator(loss_fn, compute_dtype, accum)
        pld_fn = self._pld_theta_fn()
        # Offload×DP: emit the gradient as a flat [D, chunk] array sharded
        # over the data axis — each process D2H-pulls only its shard (1/D
        # of the wire), the stage-2 partition the reference implements
        # with per-rank IPG buckets (stage2.py:613-738).
        flat_dp = (self._off_D, self._off_chunk) if self._offload_dp \
            else None
        mesh = self.mesh
        detect, nan_skip, fault_on = self._nan_guard_flags()
        self._fault_arg = fault_on

        def grad_step(params, dstate, batch, rng, lr_in, grad_fault=None):
            scale = dstate.loss_scale.cur_scale if (fp16 and dynamic) \
                else jnp.asarray(static_scale, jnp.float32)
            loss_kw = {"pld_theta": pld_fn(dstate.global_step)} \
                if pld_fn is not None else None
            loss_sum, grads = accumulate(params, batch, rng, scale, loss_kw)
            if fault_on:
                grads = jax.tree_util.tree_map(lambda g: g * grad_fault,
                                               grads)
            # No ZeRO grad-sharding constraint on the TREE: single-process
            # offload fetches the full gradient to host RAM; offload×DP
            # instead reshards the FLAT gradient to [D, chunk] over the
            # data axis below (flat_dp) so each process pulls only its
            # 1/D shard — the stage-2 partition, applied post-epilogue.
            grads, overflow, nonfinite, grad_norm, applied_norm = \
                grad_epilogue(grads, scale, accum, fp16, clip,
                              detect_nonfinite=detect, nan_skip=nan_skip)
            if grads_16bit:
                # Reference parity: stage-2 offload moves fp16 grads to
                # pinned host memory (stage2.py:793) — 16-bit halves the
                # D2H wire; the host C++ Adam widens to fp32 during its
                # existing copy into the flat grad buffer (no extra pass).
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(compute_dtype), grads)
            lr = lr_fn(dstate.global_step) if lr_fn is not None else lr_in
            beta1 = mom_fn(dstate.global_step)
            dstate_out = loss_scale_epilogue(dstate, overflow, fp16, dynamic,
                                             scale_args)
            metrics = step_metrics(loss_sum, accum, grad_norm, applied_norm,
                                   lr, scale, overflow, dstate=dstate_out,
                                   nonfinite=nonfinite)
            metrics["beta1"] = beta1
            if flat_dp is not None:
                D, chunk = flat_dp
                leaves = jax.tree_util.tree_leaves(grads)
                flat = jnp.concatenate([l.reshape(-1) for l in leaves])
                flat = jnp.pad(flat, (0, D * chunk - flat.shape[0]))
                flat = jax.lax.with_sharding_constraint(
                    flat.reshape(D, chunk),
                    NamedSharding(mesh, PartitionSpec("data")))
                return flat, dstate_out, metrics
            return grads, dstate_out, metrics

        return donated_jit(grad_step, (1,))

    def _train_batch_offload(self, placed, step_rng, lr_in, fault_extra=()):
        """Host half of the offload step: pull grads, C++ Adam update on
        the masters, push compute-dtype params back (the reference's
        async_accumulate + CPUAdam.step + copy-back, stage2.py:793-1423).

        The host phase is software-pipelined (round 5): all grad D2H
        transfers start async up front, then per ~64 MB leaf-aligned
        chunk the C++ Adam (+ fused bf16 convert) of chunk k runs in a
        worker thread while chunk k+1's bytes land — the TPU analog of
        the reference's overlap design. ``last_host_phase_s`` records the
        host wall time so bench rows can report the host fraction of the
        step."""
        if self._offload_dp:
            return self._train_batch_offload_dp(placed, step_rng, lr_in,
                                                fault_extra)
        grads, self.device_state, metrics = self._compiled_train_step(
            self.params, self.device_state, placed, step_rng, lr_in,
            *fault_extra)
        if not bool(metrics["overflow"]):   # blocks until device step done
            t0 = time.perf_counter()
            # Nested under the caller's `dispatch` span: the host-Adam
            # phase shows up as its own range inside the step's dispatch
            # window on both the event log and the xplane trace.
            with (self.telemetry.span if self.telemetry is not None
                  else null_span)("host_adam"):
                opt = self.cpu_optimizer
                bf16 = self.compute_dtype == jnp.bfloat16
                lr, b1 = float(metrics["lr"]), float(metrics["beta1"])
                if bf16:
                    # Chunked copy-back: each chunk's leaves start their
                    # H2D upload (device_put is async) as soon as its
                    # Adam + bf16 convert lands, overlapping the
                    # remaining chunks' host compute. Safe to upload
                    # views of the shared bf16 buffer: it is next
                    # rewritten only after the following device step has
                    # consumed these params.
                    import ml_dtypes
                    shard_leaves = jax.tree_util.tree_leaves(
                        self._shardings["param"])
                    uploaded = [None] * len(opt.sizes)

                    def upload_chunk(li, lj):
                        flat = opt._bf16_buf.view(ml_dtypes.bfloat16)
                        for i in range(li, lj):
                            o, sz = opt.offsets[i], opt.sizes[i]
                            uploaded[i] = jax.device_put(
                                flat[o:o + sz].reshape(opt.shapes[i]),
                                shard_leaves[i])

                    opt.step_overlapped(
                        grads, lr=lr, beta1=b1, bf16_out=True,
                        chunk_bytes=self._offload_chunk_bytes,
                        on_chunk=upload_chunk)
                    self.params = jax.tree_util.tree_unflatten(
                        opt.treedef, uploaded)
                else:
                    opt.step_overlapped(
                        grads, lr=lr, beta1=b1,
                        chunk_bytes=self._offload_chunk_bytes)
                    self.params = self._upload_offload_params()
            self.last_host_phase_s = time.perf_counter() - t0
        return metrics

    def _train_batch_offload_dp(self, placed, step_rng, lr_in,
                                fault_extra=()):
        """Offload×DP host phase (reference stage-2 offload semantics):
        pull only this process's shard of the flat gradient, C++ Adam on
        the matching contiguous master range, reassemble full params on
        device via the XLA all-gather in the assemble jit. Host work and
        wire bytes are 1/D per process — DP over processes IS the
        parallelism (the reference parallelizes its CPU Adam the same
        way: each rank steps its own partition).

        Within the rank the phase is pipelined PER DATA-AXIS ROW, same
        worker pattern as the single-process path: row r+1's grad bytes
        land (blocking only on that row's async D2H) while the worker
        runs Adam + convert on row r, and each row's updated params
        start their H2D the moment its future resolves."""
        flat_shard, self.device_state, metrics = self._compiled_train_step(
            self.params, self.device_state, placed, step_rng, lr_in,
            *fault_extra)
        if bool(metrics["overflow"]):
            return metrics
        t0 = time.perf_counter()
        opt = self.cpu_optimizer
        D, chunk = self._off_D, self._off_chunk
        sharding, ranges = self._local_row_ranges()
        shards = {s.index[0].start or 0: s.data
                  for s in flat_shard.addressable_shards}
        for data in shards.values():
            start = getattr(data, "copy_to_host_async", None)
            if start is not None:
                start()
        rows = [r for r, *_ in ranges]
        assert rows == list(range(rows[0], rows[-1] + 1)), (
            f"non-contiguous local grad rows {rows}: the flat-shard "
            "partition assumes process-major device order on the data "
            "axis")
        assert set(rows) == set(shards), (rows, sorted(shards))
        bf16 = self.compute_dtype == jnp.bfloat16
        if bf16 and opt._bf16_buf is None:
            opt._bf16_buf = np.empty(opt.total, np.uint16)
        if opt._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            opt._pool = ThreadPoolExecutor(max_workers=1)
        opt._step += 1
        lr, b1 = float(metrics["lr"]), float(metrics["beta1"])
        futs = []
        try:
            for r, lo, n, _ in ranges:
                if n:
                    opt._grad_buf[lo:lo + n] = np.asarray(
                        shards[r], np.float32).reshape(-1)[:n]
                futs.append(opt.submit_update_range(
                    opt._step, lr, b1, lo, n, bf16) if n else None)
            if bf16:
                import ml_dtypes
                src, np_dtype = opt._bf16_buf.view(ml_dtypes.bfloat16), \
                    ml_dtypes.bfloat16
            else:
                src, np_dtype = opt.master, np.dtype(self.compute_dtype)
            arrays = []
            for (r, lo, n, d), f in zip(ranges, futs):
                if f is not None:
                    opt.drain_update(f, opt._step, lr, b1, lo, n, bf16)
                if n == chunk and src.dtype == np_dtype:
                    row = src[lo:lo + chunk].reshape(1, chunk)
                else:
                    row = np.zeros((1, chunk), np_dtype)
                    if n:
                        row[0, :n] = src[lo:lo + n]
                arrays.append(jax.device_put(row, d))
        finally:
            # On any failure above, no submitted Adam range may still be
            # running (or queued) once we unwind: the worker mutates the
            # shared master/moment buffers, and the next train_batch —
            # or interpreter teardown — would race it. Cancel what never
            # started, drain what did; secondary errors must not mask
            # the original exception.
            for f in futs:
                if f is not None and not f.cancel():
                    try:
                        f.result()
                    except Exception:
                        pass
        garr = jax.make_array_from_single_device_arrays(
            (D, chunk), sharding, arrays)
        self.params = self._offload_assemble_jit()(garr)
        self.last_host_phase_s = time.perf_counter() - t0
        return metrics

    def _local_row_ranges(self):
        """The host-range ↔ data-axis-row mapping for offload×DP — THE
        one place it lives (per-step reassembly and the checkpoint
        gather both iterate it): ``(sharding, [(row, lo, n, device)])``
        for this process's addressable rows of the global [D, chunk]
        flat layout, ``n`` clipped at ``total`` (the last row carries
        padding)."""
        opt = self.cpu_optimizer
        D, chunk, total = self._off_D, self._off_chunk, opt.total
        sharding = NamedSharding(self.mesh, PartitionSpec("data"))
        imap = sharding.devices_indices_map((D, chunk))
        rows = []
        for d in sharding.addressable_devices:
            r = imap[d][0].start or 0
            lo = r * chunk
            rows.append((r, lo, max(0, min(chunk, total - lo)), d))
        rows.sort()
        return sharding, rows

    def _scatter_local_rows(self, src, np_dtype):
        """Global [D, chunk] array over the data axis, each addressable
        device's row filled from this process's flat host buffer ``src``
        (zero-padded past ``total``) — the checkpoint-gather half of the
        mapping in :meth:`_local_row_ranges`."""
        D, chunk = self._off_D, self._off_chunk
        sharding, rows = self._local_row_ranges()
        arrays = []
        for _, lo, n, d in rows:
            row = np.zeros((1, chunk), np_dtype)
            if n:
                row[0, :n] = src[lo:lo + n]
            arrays.append(jax.device_put(row, d))
        return jax.make_array_from_single_device_arrays(
            (D, chunk), sharding, arrays)

    def _offload_assemble_jit(self):
        """Cached jit mapping the global data-sharded [D, chunk] flat
        param array to the engine's param pytree/shardings — XLA inserts
        the all-gather riding ICI."""
        if getattr(self, "_offload_assemble_fn", None) is None:
            opt = self.cpu_optimizer
            total = opt.total
            offsets, sizes, shapes = opt.offsets, opt.sizes, opt.shapes
            treedef = opt.treedef

            def assemble(flat2d):
                flat = flat2d.reshape(-1)[:total]
                leaves = [flat[o:o + s].reshape(shp)
                          for o, s, shp in zip(offsets, sizes, shapes)]
                return jax.tree_util.tree_unflatten(treedef, leaves)

            self._offload_assemble_fn = jax.jit(
                assemble, out_shardings=self._shardings["param"])
        return self._offload_assemble_fn

    def _offload_sync_host_state(self):
        """Make every process's full host master/moment buffers current
        (each process only updates its own range during offload×DP
        training) — an all-gather at fp32 through the device mesh, used
        before checkpointing so the saved state is complete and
        precision-lossless."""
        opt = self.cpu_optimizer
        total = opt.total
        if getattr(self, "_offload_gather_fn", None) is None:
            rep = NamedSharding(self.mesh, PartitionSpec())
            # Cached like _offload_assemble_jit: all three buffers (and
            # every later checkpoint) share one [D, chunk] program, so
            # rebuilding the jit per call just forces retrace+recompile.
            self._offload_gather_fn = jax.jit(lambda x: x,
                                              out_shardings=rep)
        gather = self._offload_gather_fn
        for buf in (opt.master, opt.exp_avg, opt.exp_avg_sq):
            garr = self._scatter_local_rows(buf, np.float32)
            buf[:] = np.asarray(gather(garr)).reshape(-1)[:total]

    def _sparse_grad_flags(self):
        """Pytree of bools (params structure): which leaves take the CSR
        sparse-gradient path. The reference auto-detects ``nn.Embedding``
        modules when ``sparse_gradients`` is on (engine.py:177-183); a
        functional engine has no modules, so detection is by param path —
        2-D leaves whose path mentions an embedding-ish name. Override per
        engine with ``engine.sparse_grad_predicate = lambda names, leaf:
        ...`` before the first ``train_batch``."""
        import re

        # "emb" only as a whole path component ("emb", "tok_emb.weight") so
        # e.g. "member" doesn't false-positive.
        pat = re.compile(
            r"embed|wte|wpe|vocab|token|lookup|(?:^|[._/])emb(?:[._/]|$)",
            re.I)
        pred = getattr(self, "sparse_grad_predicate", None) or (
            lambda names, leaf: leaf.ndim == 2 and
            any(pat.search(n) for n in names))

        def flag(path, leaf):
            names = [str(getattr(p, "key", getattr(p, "idx", p)))
                     for p in path]
            return bool(pred(names, leaf))

        flags = jax.tree_util.tree_map_with_path(flag, self.params)
        if not any(jax.tree_util.tree_leaves(flags)):
            # The reference's detection is structural (nn.Embedding,
            # engine.py:177-183) and so cannot miss; a name predicate can.
            # With sparse_gradients on and zero matches, every leaf would
            # silently take the dense path — say so loudly.
            logger.warning(
                "sparse_gradients is enabled but the embedding predicate "
                "matched NO parameter leaves — every gradient will use the "
                "dense allreduce path. Set engine.sparse_grad_predicate to "
                "select your embedding tables (param path names: %s).",
                [
                    "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                             for p in path)
                    for path, _ in
                    jax.tree_util.tree_flatten_with_path(self.params)[0]
                ][:16])
        return flags

    def _make_sparse_grad_train_step(self):
        """Compiled step with CSR sparse embedding-gradient communication
        (reference `runtime/engine.py:177-183` auto-conversion and
        `engine.py:1157-1213` sparse allreduce).

        shard_map over the ``data`` axis: each shard takes local grads;
        embedding leaves are sparsified to their top-``k`` rows by L1 mass
        (``k`` = the shard's token budget, a static over-bound on touched
        rows, so the result is exact — the analog of the reference padding
        ranks to the max nnz) and exchanged by index/value all_gather;
        every other leaf takes a dense pmean.

        Exactness: a *tied* embedding (also used as the output head, e.g.
        GPT-2 wte) gets a dense gradient through the softmax — more touched
        rows than the token budget. Such leaves take a per-leaf in-jit
        dense fallback (a pmax-replicated vote over the mass the top-``k``
        truncation would drop selects ``pmean`` instead of the CSR
        exchange), so the step is *always* exact; ``sparse_grad_dropped`` /
        ``sparse_grad_dense_fallbacks`` metrics surface the lost bandwidth
        win and ``train_batch`` warns once; use
        ``engine.sparse_grad_predicate`` to exclude such leaves up front."""
        from deepspeed_tpu.runtime.csr_tensor import (csr_allreduce,
                                                      dense_to_csr)

        for ax, size in self.mesh.shape.items():
            assert ax == "data" or size == 1, (
                f"sparse_gradients supports pure data parallelism; mesh "
                f"axis {ax!r} has size {size}")
        assert self.zero_optimization_stage() == 0, (
            "sparse_gradients is incompatible with ZeRO (the reference's "
            "CSR path is the non-ZeRO allreduce fallback, engine.py:1127)")

        accum = self._engine_accum_steps()
        compute_dtype = self.compute_dtype
        fp16 = self._config.fp16_enabled
        clip = float(self._config.gradient_clipping or 0.0)
        lr_fn = self._lr_fn
        mom_fn = self._mom_fn
        opt_update = self._opt_update
        loss_fn = self.loss_fn
        scale_args = self._scale_args()
        dynamic = self.dynamic_loss_scale
        static_scale = self.static_loss_scale
        accumulate = make_grad_accumulator(loss_fn, compute_dtype, accum)
        sparse_flags = self._sparse_grad_flags()
        pld_fn = self._pld_theta_fn()
        detect, nan_skip, fault_on = self._nan_guard_flags()
        if fault_on:
            log_dist("fault_injection: the sparse-grad step does not take "
                     "the grad_fault argument; NaN-grad injection is inert "
                     "on this path", ranks=[0])

        def step_local(params, opt_state, dstate, batch, rng, lr_in):
            scale = dstate.loss_scale.cur_scale if (fp16 and dynamic) \
                else jnp.asarray(static_scale, jnp.float32)
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            loss_kw = {"pld_theta": pld_fn(dstate.global_step)} \
                if pld_fn is not None else None
            loss_sum, grads = accumulate(params, batch, rng, scale, loss_kw)

            # Static token budget: rows touched locally per boundary is
            # bounded by the number of id elements in the local batch.
            tokens = sum(
                leaf.size for leaf in jax.tree_util.tree_leaves(batch)
                if jnp.issubdtype(leaf.dtype, jnp.integer))

            dropped = jnp.asarray(0.0, jnp.float32)
            fallbacks = jnp.asarray(0, jnp.int32)

            def reduce_leaf(is_sparse, g):
                nonlocal dropped, fallbacks
                if is_sparse and 0 < tokens < g.shape[0]:
                    csr = dense_to_csr(g, min(tokens, g.shape[0]))
                    # L1 mass the static top-k truncation would lose.
                    # Meaningfully nonzero ⇒ this leaf's grad is denser
                    # than the token budget (e.g. a *tied* embedding,
                    # whose LM-head softmax grad is dense over the vocab)
                    # — truncating would silently drop real gradient every
                    # step, so the leaf falls back to the exact dense
                    # pmean. The vote compares *relative* mass (full-array
                    # and top-k reductions round differently — an absolute
                    # >0 test would flap on ULP noise) and is pmax'd so
                    # every shard takes the same cond branch.
                    g_l1 = jnp.abs(g).sum().astype(jnp.float32)
                    leaf_dropped = jax.lax.pmax(
                        (g_l1 -
                         jnp.abs(csr.values).sum()).astype(jnp.float32),
                        "data")
                    use_dense = leaf_dropped > 1e-6 * jax.lax.pmax(
                        g_l1, "data")
                    # only count mass when the vote fires — below the
                    # relative threshold it is reduction-order noise
                    dropped += jnp.where(use_dense, leaf_dropped, 0.0)
                    fallbacks += use_dense.astype(jnp.int32)
                    return jax.lax.cond(
                        use_dense,
                        lambda: jax.lax.pmean(g, "data"),
                        lambda: csr_allreduce(csr, "data").to_dense())
                return jax.lax.pmean(g, "data")

            grads = jax.tree_util.tree_map(reduce_leaf, sparse_flags, grads)

            # Grads are now replicated-global, so no cross-shard vote or
            # norm reduction is needed past this point.
            grads, overflow, nonfinite, grad_norm, applied_norm = \
                grad_epilogue(grads, scale, accum, fp16, clip,
                              detect_nonfinite=detect, nan_skip=nan_skip)

            lr = lr_fn(dstate.global_step) if lr_fn is not None else lr_in
            beta1 = mom_fn(dstate.global_step)
            new_params, new_opt = opt_update(params, grads, opt_state, lr,
                                             beta1)

            def select(old, new):
                return jax.tree_util.tree_map(
                    lambda o, n: jnp.where(overflow, o, n), old, new)
            params_out = select(params, new_params)
            opt_out = type(opt_state)(
                m=select(opt_state.m, new_opt.m),
                v=select(opt_state.v, new_opt.v),
                step=jnp.where(overflow, opt_state.step, new_opt.step))

            dstate_out = loss_scale_epilogue(dstate, overflow, fp16, dynamic,
                                             scale_args)
            metrics = step_metrics(
                loss_sum, accum, grad_norm, applied_norm, lr, scale,
                overflow, loss_reduce=lambda l: jax.lax.pmean(l, "data"),
                dstate=dstate_out, nonfinite=nonfinite)
            metrics["sparse_grad_dropped"] = dropped
            metrics["sparse_grad_dense_fallbacks"] = fallbacks
            return params_out, opt_out, dstate_out, metrics

        P = PartitionSpec
        rep = P()
        param_specs = jax.tree_util.tree_map(lambda _: rep, self.params)
        opt_specs = type(self.opt_state)(
            m=jax.tree_util.tree_map(lambda _: rep, self.opt_state.m),
            v=jax.tree_util.tree_map(lambda _: rep, self.opt_state.v),
            step=rep)
        dstate_specs = jax.tree_util.tree_map(lambda _: rep,
                                              self.device_state)
        metrics_specs = {k: rep for k in ("loss", "grad_norm",
                                          "applied_grad_norm", "lr",
                                          "loss_scale", "overflow",
                                          "skipped_steps",
                                          "consecutive_skipped_steps",
                                          "grad_nonfinite",
                                          "sparse_grad_dropped",
                                          "sparse_grad_dense_fallbacks")}
        mapped = shard_map(
            step_local, mesh=self.mesh,
            in_specs=(param_specs, opt_specs, dstate_specs, P(None, "data"),
                      rep, rep),
            out_specs=(param_specs, opt_specs, dstate_specs, metrics_specs),
            check_vma=False)
        return donated_jit(mapped, (0, 1, 2))

    def _make_onebit_train_step(self):
        """Compiled 1-bit Adam step: shard_map over the ``data`` axis so
        each shard sees *local* gradients, which the optimizer averages
        itself — densely (pmean) during warmup, with the 1-bit
        error-feedback collective after ``freeze_step`` (the analog of the
        reference disabling engine allreduce at onebit_adam.py:372 and
        running its MPI data plane)."""
        from deepspeed_tpu.runtime.fp16.onebit_adam import OnebitAdamState

        for ax, size in self.mesh.shape.items():
            assert ax == "data" or size == 1, (
                f"OneBitAdam supports pure data parallelism; mesh axis "
                f"{ax!r} has size {size}")

        accum = self._engine_accum_steps()
        compute_dtype = self.compute_dtype
        fp16 = self._config.fp16_enabled
        clip = float(self._config.gradient_clipping or 0.0)
        lr_fn = self._lr_fn
        mom_fn = self._mom_fn
        opt_update = self._opt_update
        loss_fn = self.loss_fn
        scale_args = self._scale_args()
        dynamic = self.dynamic_loss_scale
        static_scale = self.static_loss_scale
        accumulate = make_grad_accumulator(loss_fn, compute_dtype, accum)
        pld_fn = self._pld_theta_fn()
        detect, nan_skip, fault_on = self._nan_guard_flags()
        if fault_on:
            log_dist("fault_injection: the 1-bit Adam step does not take "
                     "the grad_fault argument; NaN-grad injection is inert "
                     "on this path", ranks=[0])

        def step_local(params, opt_state, dstate, batch, rng, lr_in):
            scale = dstate.loss_scale.cur_scale if (fp16 and dynamic) \
                else jnp.asarray(static_scale, jnp.float32)
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            loss_kw = {"pld_theta": pld_fn(dstate.global_step)} \
                if pld_fn is not None else None
            loss_sum, grads = accumulate(params, batch, rng, scale, loss_kw)

            # Cross-shard overflow vote (reference stage2.py:1527-1551);
            # norms are pmean'd local-shard diagnostics (a true global norm
            # would need the dense allreduce this optimizer avoids), and
            # clipping scales by the pmax norm so every shard applies the
            # same (conservative, rank-consistent) factor.
            grads, overflow, nonfinite, grad_norm, applied_norm = \
                grad_epilogue(
                    grads, scale, accum, fp16, clip,
                    vote=lambda o: jax.lax.pmax(
                        o.astype(jnp.int32), "data") > 0,
                    norm_reduce=lambda n: jax.lax.pmean(n, "data"),
                    clip_norm_reduce=lambda n: jax.lax.pmax(n, "data"),
                    detect_nonfinite=detect, nan_skip=nan_skip)

            lr = lr_fn(dstate.global_step) if lr_fn is not None else lr_in
            beta1 = mom_fn(dstate.global_step)
            new_params, new_opt = opt_update(params, grads, opt_state, lr,
                                             beta1)

            def select(old, new):
                return jax.tree_util.tree_map(
                    lambda o, n: jnp.where(overflow, o, n), old, new)
            params_out = select(params, new_params)
            opt_out = OnebitAdamState(
                m=select(opt_state.m, new_opt.m),
                v=select(opt_state.v, new_opt.v),
                step=jnp.where(overflow, opt_state.step, new_opt.step),
                worker_error=select(opt_state.worker_error,
                                    new_opt.worker_error),
                server_error=select(opt_state.server_error,
                                    new_opt.server_error))

            dstate_out = loss_scale_epilogue(dstate, overflow, fp16, dynamic,
                                             scale_args)
            metrics = step_metrics(
                loss_sum, accum, grad_norm, applied_norm, lr, scale,
                overflow, loss_reduce=lambda l: jax.lax.pmean(l, "data"),
                dstate=dstate_out, nonfinite=nonfinite)
            return params_out, opt_out, dstate_out, metrics

        P = PartitionSpec
        rep = P()
        opt_specs = OnebitAdamState(
            m=jax.tree_util.tree_map(lambda _: rep, self.opt_state.m),
            v=jax.tree_util.tree_map(lambda _: rep, self.opt_state.v),
            step=rep, worker_error=P("data", None), server_error=P("data"))
        param_specs = jax.tree_util.tree_map(lambda _: rep, self.params)
        dstate_specs = jax.tree_util.tree_map(lambda _: rep,
                                              self.device_state)
        metrics_specs = {k: rep for k in ("loss", "grad_norm",
                                          "applied_grad_norm", "lr",
                                          "loss_scale", "overflow",
                                          "skipped_steps",
                                          "consecutive_skipped_steps",
                                          "grad_nonfinite")}
        mapped = shard_map(
            step_local, mesh=self.mesh,
            in_specs=(param_specs, opt_specs, dstate_specs, P(None, "data"),
                      rep, rep),
            out_specs=(param_specs, opt_specs, dstate_specs, metrics_specs),
            check_vma=False)
        return donated_jit(mapped, (0, 1, 2))

    def _make_pipeline_onebit_train_step(self):
        """Compiled step for the pipeline x 1-bit Adam composition
        (BASELINE config 5; beyond the reference, whose OnebitAdam rides
        the fp16-optimizer path only): the 1F1B program runs with
        ``data_local=True`` — its dense psum over ``data`` is skipped and
        gradients come back with a stacked data axis — then the 1-bit
        error-feedback collective + update runs in a second ``shard_map``
        over (pipe, data), each stage group averaging its own shard's
        momentum over its data replicas.

        Metric semantics: ``grad_norm`` here is the MEAN of the
        per-data-replica local gradient norms (and clipping scales by the
        MAX of them), not the norm of the data-averaged gradient that the
        dense train steps report. The data-averaged gradient is never
        formed on this path — materializing it (even just for its norm,
        whose square sums cross-replica products) would reintroduce the
        dense all-reduce the 1-bit collective exists to eliminate. The
        mean-of-norms upper-bounds the true averaged-gradient norm
        (triangle inequality), so treat it as a stability indicator, not
        a cross-config-comparable quantity."""
        from deepspeed_tpu.runtime.fp16.onebit_adam import OnebitAdamState

        for ax, size in self.mesh.shape.items():
            assert ax in ("data", "pipe", "model") or size == 1, (
                f"pipeline OneBitAdam supports pipe x model x data meshes; "
                f"axis {ax!r} has size {size}")
        direct_local = self.loss_fn.direct_value_and_grad_local
        fp16 = self._config.fp16_enabled
        clip = float(self._config.gradient_clipping or 0.0)
        lr_fn = self._lr_fn
        mom_fn = self._mom_fn
        opt_update = self._opt_update
        scale_args = self._scale_args()
        dynamic = self.dynamic_loss_scale
        static_scale = self.static_loss_scale
        mesh = self.mesh
        model_size = mesh.shape.get("model", 1)
        tree_map = jax.tree_util.tree_map
        detect, nan_skip, fault_on = self._nan_guard_flags()
        if fault_on:
            log_dist("fault_injection: the pipeline 1-bit step does not "
                     "take the grad_fault argument; NaN-grad injection is "
                     "inert on this path", ranks=[0])

        P = PartitionSpec
        param_specs = tree_map(lambda ns: ns.spec, self._shardings["param"])
        grad_specs = tree_map(lambda sp: P("data", *tuple(sp)), param_specs)
        err_spec = (P("pipe", "model", "data", None) if model_size > 1
                    else P("pipe", "data", None))

        from deepspeed_tpu.runtime.fp16.onebit_adam import (
            pipeline_onebit_splits)
        splits = pipeline_onebit_splits(
            self.params, self.dp_world_size, mesh.shape["pipe"], model_size)
        if model_size > 1:
            (pm, cm), (pb, cb), (pr, cr) = splits
            # static mask: which body leaves are model-sharded (mp_*) —
            # they compress separately from the model-replicated leaves
            # so replicated copies see the same quantization scale on
            # every model rank. Shared source of truth with the buffer
            # sizing (onebit_adam.pipeline_mp_mask).
            from deepspeed_tpu.runtime.fp16.onebit_adam import (
                pipeline_mp_mask)
            mp_mask = pipeline_mp_mask(self.params, model_size)
        else:
            (pb, cb), (pr, cr) = splits
            pm = cm = 0
            mp_mask = None

        def split_body(tree):
            """Local body tree → (mp leaves, replicated leaves) as list
            pytrees, in tree_leaves order."""
            leaves = jax.tree_util.tree_leaves(tree)
            mp = [x for x, is_mp in zip(leaves, mp_mask) if is_mp]
            rep = [x for x, is_mp in zip(leaves, mp_mask) if not is_mp]
            return mp, rep

        def merge_body(mp, rep, template):
            mp_it, rep_it = iter(mp), iter(rep)
            leaves = [next(mp_it) if is_mp else next(rep_it)
                      for is_mp in mp_mask]
            treedef = jax.tree_util.tree_structure(template)
            return jax.tree_util.tree_unflatten(treedef, leaves)

        def upd(p_l, g_l, m_l, v_l, we_l, se_l, step, lr_, b1, ovf):
            # Groups that share content compress SEPARATE buffers (a
            # joint one couples the quantization scale and silently
            # diverges the shared copies): body vs pipe-replicated rest;
            # under 3D also model-sharded mp leaves vs model-replicated
            # body leaves.
            body_p = {"body": tree_map(lambda a: a[0], p_l["body"])}
            body_g = {"body": tree_map(lambda a: a[0, 0], g_l["body"])}
            body_m = {"body": tree_map(lambda a: a[0], m_l["body"])}
            body_v = {"body": tree_map(lambda a: a[0], v_l["body"])}
            rest_keys = ("prologue", "epilogue", "tied")
            rest_p = {k: p_l[k] for k in rest_keys}
            rest_g = {k: tree_map(lambda a: a[0], g_l[k])
                      for k in rest_keys}
            rest_m = {k: m_l[k] for k in rest_keys}
            rest_v = {k: v_l[k] for k in rest_keys}

            we = we_l[0, 0] if model_size > 1 else we_l[0]   # [1, P]
            se = se_l[0, 0, 0] if model_size > 1 else se_l[0, 0]  # [C]

            def slice_state(m_t, v_t, w_lo, w_hi, c_lo, c_hi):
                return OnebitAdamState(
                    m=m_t, v=v_t, step=step,
                    worker_error=we[:, w_lo:w_hi],
                    server_error=se[c_lo:c_hi])

            groups = []      # (params, grads, state) per compressed group
            if model_size > 1:
                mp_p, rep_p = split_body(body_p)
                mp_g, rep_g = split_body(body_g)
                mp_m, rep_m = split_body(body_m)
                mp_v, rep_v = split_body(body_v)
                groups.append((mp_p, mp_g,
                               slice_state(mp_m, mp_v, 0, pm, 0, cm)))
                groups.append((rep_p, rep_g,
                               slice_state(rep_m, rep_v,
                                           pm, pm + pb, cm, cm + cb)))
            else:
                groups.append((body_p, body_g,
                               slice_state(body_m, body_v, 0, pb, 0, cb)))
            groups.append((rest_p, rest_g,
                           slice_state(rest_m, rest_v,
                                       pm + pb, pm + pb + pr,
                                       cm + cb, cm + cb + cr)))

            results = [opt_update(p, g, st, lr_, b1)
                       for p, g, st in groups]

            def sel(old, new):
                return tree_map(lambda o, n: jnp.where(ovf, o, n), old, new)

            new_rp, new_rst = results[-1]
            if model_size > 1:
                (mp_np, mp_nst), (rep_np, rep_nst) = results[0], results[1]
                new_body_p = merge_body(sel(groups[0][0], mp_np),
                                        sel(groups[1][0], rep_np),
                                        body_p)["body"]
                new_body_m = merge_body(sel(groups[0][2].m, mp_nst.m),
                                        sel(groups[1][2].m, rep_nst.m),
                                        body_p)["body"]
                new_body_v = merge_body(sel(groups[0][2].v, mp_nst.v),
                                        sel(groups[1][2].v, rep_nst.v),
                                        body_p)["body"]
                body_states = [mp_nst, rep_nst]
            else:
                new_bp, new_bst = results[0]
                new_body_p = sel(body_p, new_bp)["body"]
                new_body_m = sel(body_m, new_bst.m)["body"]
                new_body_v = sel(body_v, new_bst.v)["body"]
                body_states = [new_bst]
            new_p = dict(sel(rest_p, new_rp), body=new_body_p)
            new_m = dict(sel(rest_m, new_rst.m), body=new_body_m)
            new_v = dict(sel(rest_v, new_rst.v), body=new_body_v)
            new_we = jnp.where(
                ovf, we, jnp.concatenate(
                    [st.worker_error for st in body_states]
                    + [new_rst.worker_error], axis=-1))
            new_se = jnp.where(
                ovf, se, jnp.concatenate(
                    [st.server_error for st in body_states]
                    + [new_rst.server_error], axis=-1))
            new_step = jnp.where(ovf, step, new_rst.step)

            def restore_body(t):
                return dict(t, body=tree_map(lambda a: a[None], t["body"]))
            if model_size > 1:
                we_out, se_out = new_we[None, None], new_se[None, None, None]
            else:
                we_out, se_out = new_we[None], new_se[None, None]
            return (restore_body(new_p), restore_body(new_m),
                    restore_body(new_v), we_out, se_out, new_step)

        mapped_upd = shard_map(
            upd, mesh=mesh,
            in_specs=(param_specs, grad_specs, param_specs, param_specs,
                      err_spec, err_spec, P(), P(), P(), P()),
            out_specs=(param_specs, param_specs, param_specs, err_spec,
                       err_spec, P()),
            check_vma=False)

        def train_step(params, opt_state, dstate, batch, rng, lr_in):
            scale = dstate.loss_scale.cur_scale if (fp16 and dynamic) \
                else jnp.asarray(static_scale, jnp.float32)
            micro = tree_map(lambda x: x[0], batch)   # accum dim == 1
            loss, grads = direct_local(params, micro, rng, scale)

            # Unscale + overflow + clip on the STACKED (data-local) grads
            # — reductions only, never a dense cross-data averaging.
            grads = tree_map(lambda g: g.astype(jnp.float32) / scale, grads)
            nonfinite = check_overflow(grads) if (fp16 or detect) \
                else jnp.asarray(False)
            overflow = nonfinite if (fp16 or nan_skip) else jnp.asarray(False)
            # Per-data-slice norms: sum of squares over every dim but the
            # stacked axis; identical on all ranks, so clipping by the max
            # slice norm is rank-consistent (the DP onebit's pmax analog).
            sq = sum(jnp.sum(jnp.square(g),
                             axis=tuple(range(1, g.ndim)))
                     for g in jax.tree_util.tree_leaves(grads))
            norms = jnp.sqrt(sq)                        # [data]
            # mean of local norms, NOT the averaged-gradient norm — see
            # the method docstring for why that is the only choice here.
            grad_norm = jnp.mean(norms)
            applied_norm = grad_norm
            if clip > 0:
                factor = jnp.minimum(
                    1.0, clip / (jnp.max(norms) + 1e-6))
                grads = tree_map(lambda g: g * factor, grads)
                applied_norm = grad_norm * factor

            lr = lr_fn(dstate.global_step) if lr_fn is not None else lr_in
            beta1 = mom_fn(dstate.global_step)
            new_params, new_m, new_v, new_we, new_se, new_step = mapped_upd(
                params, grads, opt_state.m, opt_state.v,
                opt_state.worker_error, opt_state.server_error,
                opt_state.step, lr, beta1, overflow)
            opt_out = OnebitAdamState(m=new_m, v=new_v, step=new_step,
                                      worker_error=new_we,
                                      server_error=new_se)
            dstate_out = loss_scale_epilogue(dstate, overflow, fp16,
                                             dynamic, scale_args)
            metrics = step_metrics(loss, 1, grad_norm, applied_norm, lr,
                                   scale, overflow, dstate=dstate_out,
                                   nonfinite=nonfinite)
            return new_params, opt_out, dstate_out, metrics

        return donated_jit(train_step, (0, 1, 2))

    def _shard_batch(self, batch):
        """Host-side: this process's batch rows → [accum, per_step_global, ...]
        with the per-step dim sharded over ``data``.

        Single-host: the caller passes the full global batch
        (``train_batch_size`` rows). Multi-host: each process passes its
        ``train_batch_size // process_count`` share (what
        DeepSpeedDataLoader emits) and the global array is assembled from
        the per-process shards.
        """
        accum = self._engine_accum_steps()
        sharding = NamedSharding(self.mesh, PartitionSpec(None, "data"))
        n_proc = jax.process_count()
        expected = self._config.train_batch_size // n_proc

        def place(x):
            x = np.asarray(x)
            assert x.shape[0] == expected, (
                f"train_batch expects {expected} rows per process "
                f"(train_batch_size {self._config.train_batch_size} / "
                f"{n_proc} processes), got {x.shape[0]}")
            x = x.reshape((accum, x.shape[0] // accum) + x.shape[1:])
            if n_proc == 1:
                return jax.device_put(x, sharding)
            return jax.make_array_from_process_local_data(sharding, x)

        return jax.tree_util.tree_map(place, batch)

    # ------------------------------------------------------------------
    # telemetry helpers
    # ------------------------------------------------------------------
    def _telemetry_flavor(self):
        """The step flavor stamped on telemetry events (audit taxonomy:
        dense/zero1-3/offload/quantized/pipeline/onebit/sparse)."""
        cached = getattr(self, "_telemetry_flavor_cache", None)
        if cached is None:
            from deepspeed_tpu.analysis.audit import _engine_flavor
            try:
                cached = _engine_flavor(self)
            except Exception:
                cached = "unknown"
            self._telemetry_flavor_cache = cached
        return cached

    @staticmethod
    def _scalar_metrics(metrics):
        """Host-scalar view of a step's metrics dict for the step event
        (missing keys — pipeline flavor, guards off — are just absent)."""
        out = {}
        for key in ("loss", "lr", "grad_norm", "applied_grad_norm",
                    "loss_scale"):
            if key in metrics:
                try:
                    out[key] = float(metrics[key])
                except Exception:
                    pass
        for key, cast in (("overflow", bool), ("grad_nonfinite", bool),
                          ("skipped_steps", int),
                          ("consecutive_skipped_steps", int)):
            if key in metrics:
                try:
                    out[key] = cast(metrics[key])
                except Exception:
                    pass
        return out

    def _stamp_compile_facts(self, placed, step_rng, lr_in,
                             compile_seconds=None):
        """Emit the one-shot ``compile`` event: static facts of the
        compiled step so the run's log is self-describing. Reuses the
        analysis block's audit stats when that ran; otherwise (with
        ``telemetry.stamp_static_facts``) lowers the already-compiled
        step once (a jit-cache hit on the XLA side of the same avals)
        and extracts the collective/peak-memory accounting directly."""
        tl = self._config.telemetry
        facts = {"step": self.global_steps,
                 "flavor": self._telemetry_flavor(),
                 "flops_per_token": tl.flops_per_token or None,
                 "batch_tokens": self._batch_tokens}
        if compile_seconds is not None:
            facts["compile_seconds"] = round(compile_seconds, 4)
        if self._config.compilation_cache_dir:
            from deepspeed_tpu.telemetry import compile_cache
            cc = compile_cache.counts()
            facts["compile_cache_hits"] = cc["hits"]
            facts["compile_cache_misses"] = cc["misses"]
        stats = None
        if self.last_audit_report is not None:
            stats = self.last_audit_report.stats
        elif tl.stamp_static_facts:
            try:
                from deepspeed_tpu.analysis.audit import (
                    _engine_fn_args, audit_hlo)
                fn, args = _engine_fn_args(self, placed, step_rng, lr_in)
                hlo_text = fn.lower(*args).compile().as_text()
                stats = audit_hlo(
                    hlo_text, rules=[],
                    n_devices=int(self.mesh.shape.get("data", 1))).stats
            except Exception as e:   # stamping is best-effort telemetry
                facts["static_facts_error"] = str(e)
        if stats:
            cb = stats.get("collective_bytes") or {}
            facts["collective_bytes"] = {k: int(v)
                                         for k, v in cb.items()}
            bd = stats.get("collective_bytes_by_dtype") or {}
            if bd:
                # Per-element-dtype wire accounting: what separates an
                # fp8/int8 quantized wire (u8/s8/f8 bytes) from full-
                # precision traffic sharing the same op family.
                facts["collective_bytes_by_dtype"] = {
                    op: ({dt: int(b) for dt, b in d.items()}
                         if isinstance(d, dict) else int(d))
                    for op, d in bd.items()}
            facts["while_loops"] = stats.get("while_loops")
            pm = stats.get("peak_memory") or {}
            if pm:
                facts["static_peak_bytes"] = int(pm.get("peak_bytes", 0))
                facts["static_temp_peak_bytes"] = int(
                    pm.get("temp_peak_bytes", 0))
            # engine-context audits carry the live param-tree bytes;
            # the HLO-only path falls back to the compiled program's
            # parameter-buffer accounting
            facts["param_bytes"] = int(stats.get("param_bytes") or
                                       pm.get("parameter_bytes") or 0)
            # sub-pallas_call kernel analysis (analysis/kernels.py),
            # present when the audit ran with kernels=True — the
            # per-kernel VMEM/DMA facts ds_tpu_metrics summary renders
            if stats.get("kernels"):
                facts["kernels"] = stats["kernels"]
        self.telemetry.emit("compile", **facts)

    # ------------------------------------------------------------------
    # public training API
    # ------------------------------------------------------------------
    def _run_compile_audit(self, placed, step_rng, lr_in):
        """Opt-in compile-time audit (``analysis`` config block): lower
        the just-compiled step, run the rule catalog over its HLO, and
        surface findings through logging — or raise
        :class:`AuditError` when ``fail_on_findings`` is set."""
        report = audit_compiled_step(self, placed, step_rng, lr_in,
                                     rules=self._config.analysis.rules)
        self.last_audit_report = report
        cb = report.stats.get("collective_bytes", {})
        log_dist(
            f"analysis: audited compiled {report.flavor} step — "
            f"{len(report.findings)} finding(s), "
            f"{cb.get('total', 0) / 1e6:.2f}MB collectives/step "
            f"(trip-aware)", ranks=[0])
        for f in report.findings:
            log_dist(f"analysis[{f.rule}/{f.severity}]: {f.message}",
                     ranks=[0])
        if not report.ok and self._config.analysis.fail_on_findings:
            raise AuditError(report)

    def train_batch(self, batch=None):
        """One full optimizer step over a global batch (the fast path).

        ``batch``: pytree of arrays with leading dim ``train_batch_size``,
        or None to pull from the engine dataloader.
        """
        # Step boundary: a pending preemption checkpoints + exits HERE,
        # before this step consumes a batch (the dataloader position in
        # the checkpoint must not run ahead of the optimizer state).
        self._check_preemption()
        # Telemetry-off fast path: `tele is None` is the only per-step
        # cost, and `span` degrades to a shared no-op context manager
        # (pinned by the overhead micro-benchmark test).
        tele = self.telemetry
        span = tele.span if tele is not None else null_span
        watchdog = tele.watchdog if tele is not None else None
        step_wall_t0 = time.perf_counter() if tele is not None else 0.0
        if watchdog is not None:
            watchdog.step_start(self.global_steps)
        if batch is None:
            assert self._data_iter is not None, \
                "no training_data given; pass a batch explicitly"
            with span("data_load"):
                batch = next(self._data_iter)
        first_compile = self._compiled_train_step is None
        if first_compile:
            self._compiled_train_step = self._make_offload_grad_step() \
                if self._offload else self._make_train_step()
        if tele is not None and self._batch_tokens is None:
            # Rows x second dim of the first leaf: tokens for LM batches
            # ([rows, seq] ids), rows x features otherwise — consistent
            # within a run, which is what the MFU ratio needs.
            shape = np.shape(jax.tree_util.tree_leaves(batch)[0])
            self._batch_tokens = int(shape[0]) * (
                int(shape[1]) if len(shape) > 1 else 1)
        # Fault harness: the compiled step takes a trailing grad multiplier
        # only when fault injection is configured on (no recompile or
        # signature change for ordinary runs).
        fault_extra = (jnp.asarray(
            fault_injection.grad_fault_value(self.global_steps)),) \
            if self._fault_arg else ()

        self.trace_profiler.before_step(self.global_steps)
        # sync-timing only for wall_clock_breakdown runs or steps inside
        # the trace window — never run-wide for a windowed trace config
        step_t0 = time.time() if (
            self.wall_clock_breakdown() or
            self.trace_profiler.in_window(self.global_steps)) else None
        if self.wall_clock_breakdown():
            self.timers("train_batch").start()
        self.tput_timer.start()
        with span("dispatch"):
            placed = self._shard_batch(batch)
            # Fault harness: a host-side sleep here simulates a stuck
            # collective/straggler inside the step — the watchdog test
            # seam (probe is armed-only, and only with fault injection
            # configured on).
            if self._config.resilience.fault_injection:
                # Hard process death inside the step — the supervisor
                # soak seam. For SIGKILL this call never returns.
                fault_injection.maybe_kill("step", self.global_steps)
                hang_s = fault_injection.hang_seconds(self.global_steps)
                if hang_s > 0.0:
                    with span("injected_hang"):
                        time.sleep(hang_s)
            # Derive the step rng from the CHECKPOINTED step counter rather
            # than an in-memory split chain: a resumed engine replays the
            # exact dropout masks the original would have drawn, so training
            # curves stay continuous across save/load even with dropout on.
            # Stream id 0 keeps this disjoint from backward()'s micro stream.
            step_rng = jax.random.fold_in(
                jax.random.fold_in(self._rng, 0), self.global_steps)
            lr_in = jnp.asarray(self._current_host_lr(), jnp.float32)
            # First-call wall from here through the step dispatch is
            # trace+compile (the device execution is async): the
            # `compile` event's compile_seconds, which a warm persistent
            # cache (compilation_cache_dir) should drive to near zero.
            compile_t0 = time.perf_counter() if first_compile else None
            if first_compile and self._config.analysis.enabled:
                # Compile-time audit: lowering here both triggers the one
                # real compile (the step call below is then a jit-cache
                # hit) and hands the audit the exact HLO that will execute.
                with span("compile"):
                    self._run_compile_audit(placed, step_rng, lr_in)
            # Collective confessions for the flight recorder: the first
            # call traces the step, and the overlap/ring helpers log one
            # SiteRecord per collective group they emit while tracing.
            flight = tele.flight if tele is not None else None
            sites = None
            with contextlib.ExitStack() as stack:
                if first_compile and flight is not None:
                    sites = stack.enter_context(record_collective_sites())
                if self._offload:
                    metrics = self._train_batch_offload(placed, step_rng,
                                                        lr_in, fault_extra)
                else:
                    self.params, self.opt_state, self.device_state, \
                        metrics = self._compiled_train_step(
                            self.params, self.opt_state,
                            self.device_state, placed,
                            step_rng, lr_in, *fault_extra)
            if sites is not None:
                if not sites and self.last_audit_report is not None:
                    # analysis already traced the step (our call above was
                    # a jit-cache hit); reuse the audit's captured sites
                    jx = self.last_audit_report.stats.get("jaxpr") or {}
                    sites = jx.get("collective_sites") or []
                flight.record_collectives(sites)
        if first_compile and tele is not None:
            # One-shot static facts (overlaps the step's device execution:
            # the compiled call above is still in flight).
            self._stamp_compile_facts(
                placed, step_rng, lr_in,
                compile_seconds=time.perf_counter() - compile_t0)
        if step_t0 is not None or tele is not None:
            # block on the step's own outputs BEFORE stopping any timer:
            # effects_barrier (inside the timers) only waits for
            # *effectful* dispatch, not the pure compiled train step.
            # Telemetry syncs here too — the step event's wall time must
            # cover device execution, and device_wait IS the async-
            # dispatch slack (host-bound runs show it near zero).
            with span("device_wait"):
                jax.block_until_ready(metrics["loss"])
        self.tput_timer.stop()
        if self.wall_clock_breakdown():
            self.timers("train_batch").stop()
            self.timers.log(["train_batch"],
                            memory_breakdown=self.memory_breakdown())
        if step_t0 is not None:
            self.trace_profiler.after_step(self.global_steps,
                                           time.time() - step_t0)
        else:
            self.trace_profiler.after_step(self.global_steps)

        # Only inspect the (device-resident) truncation metric on the first
        # step and at print boundaries — float() here would otherwise force
        # a host sync every step and defeat async dispatch.
        if "sparse_grad_dropped" in metrics and \
                not getattr(self, "_warned_sparse_dropped", False) and \
                (self.global_steps == 0 or (self.global_steps + 1) %
                 self._config.steps_per_print == 0):
            if float(metrics["sparse_grad_dropped"]) > 1e-7:
                self._warned_sparse_dropped = True
                logger.warning(
                    "sparse_gradients: %d embedding leaf/leaves had "
                    "gradients denser than the token budget (%.3e L1 mass "
                    "beyond top-k — tied output head?) and fell back to "
                    "the exact dense allreduce. Training is exact, but the "
                    "CSR bandwidth win is lost for those leaves; exclude "
                    "them via engine.sparse_grad_predicate to silence "
                    "this.",
                    int(metrics.get("sparse_grad_dense_fallbacks", 0)),
                    float(metrics["sparse_grad_dropped"]))

        self.micro_steps += self._config.gradient_accumulation_steps
        self.global_steps += 1

        # Recompile detector (analysis block): the step's jit cache must
        # hold exactly one entry after warm-up; growth means some input
        # changes aval every call and each step pays a fresh compile.
        an = self._config.analysis
        if an.enabled and an.check_recompile and \
                (an.rules is None or "recompile" in an.rules):
            findings = check_recompile(self,
                                       baseline=self._recompile_reported)
            if findings:
                self._recompile_reported = findings[0].details["cache_size"]
                if self.last_audit_report is not None:
                    self.last_audit_report.findings.extend(findings)
                for f in findings:
                    log_dist(f"analysis[{f.rule}/{f.severity}]: "
                             f"{f.message}", ranks=[0])
                if tele is not None:
                    tele.emit("recompile", step=self.global_steps,
                              cache_size=findings[0].details["cache_size"],
                              expected=findings[0].details["expected"],
                              message=findings[0].message)
                    self._arm_anomaly_trace("recompile")
                if an.fail_on_findings:
                    raise AuditError(AuditReport(flavor="live",
                                                 findings=findings))
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        if self.lr_scheduler is not None and \
                hasattr(self.lr_scheduler, "step"):
            self.lr_scheduler.step()

        if self._health_monitor is not None:
            # Host-side guards need the step's scalars — this is the one
            # forced device sync guards cost per step (benchmarked in the
            # resilience bench row).
            cur_scale = float(metrics["loss_scale"]) \
                if self.fp16_enabled() and self.dynamic_loss_scale else None
            trips = self._health_monitor.observe(
                step=self.global_steps - 1,
                loss=float(metrics["loss"]),
                grad_nonfinite=bool(metrics.get("grad_nonfinite",
                                                metrics["overflow"])),
                cur_scale=cur_scale)
            metrics = dict(metrics)
            metrics.update(self._health_monitor.metrics())
            for trip in trips:
                if tele is not None:
                    # Emit BEFORE applying: rollback/abort below may load
                    # a checkpoint or raise, and the trip must be on
                    # record either way.
                    tele.emit("health_guard", **trip.as_event())
                    self._arm_anomaly_trace(f"health_guard:{trip.guard}")
                self._apply_guard_trip(trip)

        rz = self._config.resilience
        if self._hot_store is not None and \
                self.global_steps % rz.hot_interval_steps == 0:
            self._hot_snapshot()
        if rz.save_interval_steps and rz.save_dir and \
                self.global_steps % rz.save_interval_steps == 0:
            self.save_checkpoint(rz.save_dir)

        self._last_metrics = metrics

        if tele is not None:
            # Per-step event: scalar metrics (already materialized by the
            # device_wait sync above, so these float()s are transfers,
            # not stalls), the drained phase spans, and the end-to-end
            # host wall time. Ring-buffered on metrics_history for
            # file-less assertions.
            step_wall = time.perf_counter() - step_wall_t0
            if watchdog is not None:
                watchdog.step_end(self.global_steps - 1, step_wall)
            evt = tele.step_event(
                step=self.global_steps,
                flavor=self._telemetry_flavor(),
                wall_s=step_wall,
                phases={k: round(v, 6)
                        for k, v in tele.drain_phases().items()},
                tokens=self._batch_tokens,
                process_index=self._proc_meta["process_index"],
                hostname=self._proc_meta["hostname"],
                **self._scalar_metrics(metrics))
            self.metrics_history.append(evt)
            if self._anomaly_detector is not None:
                reason = self._anomaly_detector.observe(step_wall)
                if reason is not None:
                    self._arm_anomaly_trace(reason)

        if self.global_steps % self._config.steps_per_print == 0:
            loss = float(metrics["loss"])
            lr = float(metrics["lr"])
            log_dist(f"step={self.global_steps}, skipped="
                     f"{self.skipped_steps}, lr={lr:.6g}, loss={loss:.5f}",
                     ranks=[0])
            summ = self.trace_profiler.summary()
            if summ is not None:
                mean_s, min_s, max_s = summ
                log_dist(f"device step time: mean={mean_s * 1e3:.1f}ms "
                         f"min={min_s * 1e3:.1f}ms max={max_s * 1e3:.1f}ms",
                         ranks=[0])
        if self.summary_writer is not None:
            self.summary_writer.add_scalar("Train/loss",
                                           float(metrics["loss"]),
                                           self.global_steps)
            self.summary_writer.add_scalar("Train/lr", float(metrics["lr"]),
                                           self.global_steps)
            if self._config.fp16_enabled:
                self.summary_writer.add_scalar(
                    "Train/loss_scale", float(metrics["loss_scale"]),
                    self.global_steps)
        return metrics["loss"]

    def eval_batch(self, batch):
        """Forward-only loss over a global batch (no grad, no state change)."""
        if self._compiled_eval_step is None:
            compute_dtype = self.compute_dtype
            loss_fn = self.loss_fn

            def eval_step(params, batch):
                cast = jax.tree_util.tree_map(
                    lambda x: x.astype(compute_dtype), params)
                return loss_fn(cast, batch, None)

            self._compiled_eval_step = jax.jit(eval_step)
        placed = self._place_rows(batch)
        return self._compiled_eval_step(self.params, placed)

    def _place_rows(self, batch):
        """Place a [rows, ...] batch sharded over ``data``; multi-host safe."""
        sharding = NamedSharding(self.mesh, PartitionSpec("data"))
        n_proc = jax.process_count()

        def place(x):
            x = np.asarray(x)
            if n_proc == 1:
                return jax.device_put(x, sharding)
            return jax.make_array_from_process_local_data(sharding, x)

        return jax.tree_util.tree_map(place, batch)

    # ------------------------------------------------------------------
    # forward/backward/step compatibility shim (reference hot-loop API)
    # ------------------------------------------------------------------
    def forward(self, batch):
        """Compatibility: compute the micro-batch loss; remember the batch so
        ``backward()`` can compute gradients for it."""
        self._pending_batch = batch
        loss = self.eval_batch(batch)
        return loss

    def __call__(self, *args, **kwargs):
        # late-bound so subclasses overriding forward() are honored
        return self.forward(*args, **kwargs)

    def backward(self, loss=None, batch=None):
        """Compatibility: accumulate gradients for the pending micro-batch.
        (In JAX the gradient comes from re-running the fused fwd+bwd program,
        not from a stored graph — prefer ``train_batch``.)"""
        if batch is None:
            batch = self._pending_batch
        assert batch is not None, "call forward(batch) first or pass batch="
        if not hasattr(self, "_micro_grad_fn"):
            compute_dtype = self.compute_dtype
            loss_fn = self.loss_fn

            def grad_fn(params, b, rng, scale):
                def f(p):
                    cast = jax.tree_util.tree_map(
                        lambda x: x.astype(compute_dtype), p)
                    loss = loss_fn(cast, b, rng)
                    return loss * scale, loss
                (_, loss), grads = jax.value_and_grad(f, has_aux=True)(params)
                return loss, grads

            self._micro_grad_fn = jax.jit(grad_fn)
        placed = self._place_rows(batch)
        # Counter-derived like train_batch's step rng (micro_steps is
        # checkpointed), so manual forward/backward loops also resume
        # with identical dropout masks. Stream id 1: a micro step must
        # never replay a train_batch step's mask even when the two
        # counters pass through equal values.
        rng = jax.random.fold_in(
            jax.random.fold_in(self._rng, 1), self.micro_steps)
        scale = jnp.asarray(self.loss_scale, jnp.float32)
        loss_val, grads = self._micro_grad_fn(self.params, placed, rng, scale)
        if self._grad_buffer is None:
            self._grad_buffer = grads
        else:
            self._grad_buffer = jax.tree_util.tree_map(
                jnp.add, self._grad_buffer, grads)
        self.micro_steps += 1
        self._pending_batch = None
        return loss_val

    def is_gradient_accumulation_boundary(self):
        return self.micro_steps % self._config.gradient_accumulation_steps == 0

    def step(self):
        """Compatibility: apply the buffered gradients at the accumulation
        boundary (reference `_take_model_step`, engine.py:922)."""
        if not self.is_gradient_accumulation_boundary():
            return
        assert self._grad_buffer is not None, "no gradients accumulated"
        accum = self._config.gradient_accumulation_steps
        denom = self.loss_scale * accum
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) / denom, self._grad_buffer)

        # fp16: overflow vote + skip + scale update (same semantics as the
        # compiled path / reference stage2.py:1341-1362).
        overflow = bool(check_overflow(grads)) if self.fp16_enabled() else False
        if not overflow:
            clip = float(self._config.gradient_clipping or 0.0)
            if clip > 0:
                grads = clip_by_global_norm(grads, clip)
            lr = self._lr_fn(self.device_state.global_step) \
                if self._lr_foldable else self._current_host_lr()
            beta1 = self._mom_fn(self.device_state.global_step)
            self.params, self.opt_state = self._opt_update(
                self.params, grads, self.opt_state, lr, beta1)
        if self.fp16_enabled() and self.dynamic_loss_scale:
            new_scale = update_loss_scale(self.device_state.loss_scale,
                                          overflow, **self._scale_args())
        else:
            new_scale = self.device_state.loss_scale
        self.device_state = DeviceState(
            loss_scale=new_scale,
            global_step=self.device_state.global_step + 1,
            skipped_steps=self.device_state.skipped_steps + int(overflow),
            consecutive_skipped=(self.device_state.consecutive_skipped + 1)
            * int(overflow))
        self._grad_buffer = None
        self.global_steps += 1
        if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "step"):
            self.lr_scheduler.step()

    # ------------------------------------------------------------------
    # checkpointing (reference engine.py:1215-1482)
    # ------------------------------------------------------------------
    def _get_ckpt_name(self, checkpoints_path, tag):
        return os.path.join(checkpoints_path, str(tag))

    def _topology(self):
        """This engine's topology fingerprint (manifest "topology" section):
        mesh shape, process count, ZeRO stage, offload flag, and the
        layer-param layout (stacked scan_layers vs per-layer) — what
        :func:`check_topology` compares on load to decide whether the
        checkpoint needs an elastic reshard."""
        from deepspeed_tpu.runtime.elastic.topology import param_layout
        return current_topology(self.mesh,
                                zero_stage=self.zero_optimization_stage(),
                                offload=self._offload,
                                param_layout=param_layout(self.params))

    def _arrays_manifest(self, state):
        """Per-leaf logical metadata (manifest "arrays" section): shape,
        dtype, and the PartitionSpec each leaf is laid out with — enough
        for the offline resharder to re-partition the checkpoint for a
        different world size without importing the model."""
        arrays = {}
        leaves_with_path, _ = jax.tree_util.tree_flatten_with_path(state)
        for path, leaf in leaves_with_path:
            sharding = getattr(leaf, "sharding", None)
            spec = getattr(sharding, "spec", None)
            if spec is None:
                spec = PartitionSpec()  # host numpy / scalar: replicated
            arrays[jax.tree_util.keystr(path)] = {
                "shape": list(np.shape(leaf)),
                "dtype": str(leaf.dtype) if hasattr(leaf, "dtype")
                else str(np.asarray(leaf).dtype),
                "spec": spec_to_json(spec),
            }
        return arrays

    def _checkpoint_state_tree(self):
        """Array pytree a checkpoint persists (the orbax payload)."""
        # Under cpu_offload the device params are a compute-dtype copy;
        # checkpoint the fp32 host masters instead so no precision is lost
        # (parity with the non-offload fp32 param save). Under offload×DP
        # each process holds only its own range fresh — gather first.
        if self._offload and getattr(self, "_offload_dp", False):
            self._offload_sync_host_state()
        ckpt_params = self.cpu_optimizer.params() if self._offload \
            else self.params
        return {
            "params": ckpt_params,
            "opt_state": self._opt_state_to_tree(),
            "device_state": {
                "cur_scale": self.device_state.loss_scale.cur_scale,
                "cur_iter": self.device_state.loss_scale.cur_iter,
                "last_overflow_iter":
                    self.device_state.loss_scale.last_overflow_iter,
                "cur_hysteresis": self.device_state.loss_scale.cur_hysteresis,
                "global_step": self.device_state.global_step,
                "skipped_steps": self.device_state.skipped_steps,
                "consecutive_skipped": self.device_state.consecutive_skipped,
            },
        }

    def _checkpoint_meta(self, client_state):
        """JSON-serializable sidecar (meta.json)."""
        return {
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            # The dropout base key: resume determinism must not depend on
            # the resuming process passing the same seed= to initialize().
            "rng_base_key": np.asarray(self._rng).tolist(),
            "dp_world_size": self.dp_world_size,
            "mp_world_size": self.mp_world_size,
            "lr_scheduler": self.lr_scheduler.state_dict()
            if self.lr_scheduler is not None and
            hasattr(self.lr_scheduler, "state_dict") else None,
            "dataloader": self._data_iter.state_dict()
            if self._data_iter is not None else None,
            "client_state": client_state or {},
        }

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        """Single logical checkpoint with sharded async-capable writes
        (orbax/tensorstore) — supersedes the reference's file-per-rank layout
        while keeping its capabilities: counters, optimizer state, loss-scale
        state, lr-scheduler state, client state, elastic dp resize on load.

        Writes are preemption-safe: the CheckpointManager stages everything
        in a tmp dir, publishes it with one atomic rename, records an
        integrity manifest, retries transient I/O errors, and prunes old
        checkpoints per ``resilience.checkpoint.keep_last_n``. Raises
        :class:`CheckpointIOError` when I/O fails past the retry budget.
        """
        if tag is None:
            tag = f"global_step{self.global_steps}"
        tele = self.telemetry
        t0 = time.perf_counter()
        with (tele.span if tele is not None else null_span)("checkpoint"):
            state = self._checkpoint_state_tree()
            meta = self._checkpoint_meta(client_state)
            extra_manifest = {
                "topology": self._topology(),
                "arrays": self._arrays_manifest(state),
            }
            path = self._ckpt_manager.save(save_dir, tag, state, meta,
                                           save_latest=save_latest,
                                           extra_manifest=extra_manifest)
        log_dist(f"saved checkpoint {path}", ranks=[0])
        if tele is not None:
            # async_save: this is the staging duration; the publish
            # rename happens on the manager's writer thread.
            tele.emit("checkpoint_save", step=self.global_steps, tag=tag,
                      path=str(path),
                      duration_s=round(time.perf_counter() - t0, 6),
                      async_save=bool(self._ckpt_manager.async_save))
        return True

    def _opt_state_to_tree(self):
        if self._offload:
            # Moments + counter only: the masters are already the
            # checkpoint's "params" entry (saving both would double the
            # parameter bytes on disk).
            state = self.cpu_optimizer.state_dict()
            state.pop("master")
            return state
        s = self.opt_state
        tree = {"m": s.m, "v": s.v, "step": s.step}
        if hasattr(s, "worker_error"):
            tree["worker_error"] = s.worker_error
            tree["server_error"] = s.server_error
        return tree

    def _opt_state_from_tree(self, tree, template):
        extra = {}
        if hasattr(template, "worker_error"):
            we, se = tree["worker_error"], tree["server_error"]
            if tuple(np.shape(we)) != tuple(template.worker_error.shape):
                # Elastic dp resize: the error-feedback buffers are shaped
                # by the saved world size and can't be repartitioned —
                # restart error feedback from zero (one step of extra
                # compression noise, then back on track).
                logger.warning(
                    "onebit error buffers saved for a different dp world "
                    "size; resetting error feedback to zero")
                we = jnp.zeros(template.worker_error.shape, jnp.float32)
                se = jnp.zeros(template.server_error.shape, jnp.float32)
            extra = {"worker_error": we, "server_error": se}
        return type(template)(m=tree["m"], v=tree["v"],
                              step=jnp.asarray(tree["step"], jnp.int32),
                              **extra)

    @staticmethod
    def _reshape_for_restage(saved_tree, template_tree, what):
        """Pipeline restage-on-load: body param leaves are stacked
        [stages, layers_per_stage, ...] and stages own contiguous layer
        ranges (partition_uniform), so a checkpoint saved under a
        different stage count holds the same layers in a different
        row-major factorization — a pure reshape restores them (the
        capability the reference's per-layer checkpoint files exist for,
        `runtime/pipe/module.py:510-567`). ONLY the [stages, layers/stage]
        leading-dim refactorization is reshaped — the per-layer payload
        dims must match exactly, so a same-element-count leaf from a
        genuinely different model (transposed kernel, repacked heads)
        still raises instead of silently loading garbage."""
        def fix(path, s, t):
            s = jnp.asarray(s)
            t_shape = tuple(t.shape)
            if s.shape == t_shape:
                return s
            # Only pipeline-body leaves are stacked [stages, layers/stage,
            # ...payload]: the leaf must live under the "body" key AND be
            # at least rank-3 with identical payload dims. A 2-D transpose
            # ([in,out] vs [out,in]) or any non-body leaf never reshapes.
            under_body = bool(path) and \
                getattr(path[0], "key", None) == "body"
            restageable = (
                under_body and s.ndim >= 3 and len(t_shape) == s.ndim and
                s.shape[2:] == t_shape[2:] and
                s.shape[0] * s.shape[1] == t_shape[0] * t_shape[1])
            if not restageable:
                raise ValueError(
                    f"checkpoint {what} leaf {jax.tree_util.keystr(path)} "
                    f"has shape {s.shape}, engine expects {t_shape}: not a "
                    "pipeline restage (only the leading [stages, "
                    "layers/stage] dims may refactor) — checkpoint is from "
                    "a different model")
            log_dist(
                f"restaging {what} leaf {jax.tree_util.keystr(path)}: "
                f"{s.shape} -> {t_shape}", ranks=[0])
            return s.reshape(t_shape)
        return jax.tree_util.tree_map_with_path(fix, saved_tree,
                                                template_tree)

    def load_checkpoint(self, load_dir, tag=None,
                        load_optimizer_states=True,
                        load_lr_scheduler_states=True):
        """Restore engine state from a checkpoint under ``load_dir``.

        ``tag=None`` loads the newest *valid* checkpoint (the ``latest``
        pointer when it validates, else a scan that skips corrupt/partial
        directories). An explicit ``tag`` is strict: a corrupt target
        raises :class:`CheckpointCorruptError` rather than silently
        loading something else.
        """
        load_t0 = time.perf_counter()
        self._ckpt_manager.wait()  # join any in-flight async save first
        resolved = self._ckpt_manager.resolve_tag(load_dir, tag)
        if resolved is None:
            logger.warning(f"no valid checkpoint found at {load_dir}; "
                           "cannot load")
            return None, {}
        # Topology gate: a checkpoint saved under a different data-parallel
        # layout only loads when elasticity is enabled (a reshard-on-load),
        # and raises the typed ElasticResumeError when the change is one no
        # relayout can absorb (tensor-parallel degree, offload toggle).
        manifest = self._ckpt_manager.validate(
            self._ckpt_manager.ckpt_path(load_dir, resolved))
        check = check_topology(
            manifest.get("topology"), self._topology(),
            elastic=bool(self._config.elasticity.enabled))
        if check.kind == "elastic":
            log_dist(
                f"elastic resume: checkpoint topology {check.changed} "
                f"differs from current mesh; resharding on load", ranks=[0])
            if self.telemetry is not None:
                self.telemetry.emit(
                    "elastic_resume", step=self.global_steps,
                    changed=check.changed,
                    dp_world_size=self.dp_world_size)
        # Restore as host numpy arrays (placement happens below on the
        # CURRENT mesh/shardings) — restoring with the saved shardings
        # trips orbax's "unsafe when restoring on a different topology"
        # path, which is exactly the elastic/restage case we support.
        restored, meta, path = self._ckpt_manager.load(load_dir, resolved)
        self._install_restored_state(
            restored, meta,
            load_optimizer_states=load_optimizer_states,
            load_lr_scheduler_states=load_lr_scheduler_states)
        log_dist(f"loaded checkpoint {path} (saved at dp="
                 f"{meta.get('dp_world_size')}, now dp={self.dp_world_size})",
                 ranks=[0])
        if self.telemetry is not None:
            self.telemetry.emit(
                "checkpoint_load", step=self.global_steps, path=str(path),
                duration_s=round(time.perf_counter() - load_t0, 6),
                topology=check.kind,
                saved_dp_world_size=meta.get("dp_world_size"),
                dp_world_size=self.dp_world_size)
        return path, meta.get("client_state", {})

    def _install_restored_state(self, restored, meta,
                                load_optimizer_states=True,
                                load_lr_scheduler_states=True):
        """Install a restored host-numpy state tree + meta sidecar into
        this engine, re-placing every leaf on the *current*
        mesh/shardings. Shared by the disk path (``load_checkpoint``)
        and the hot tier's RAM/mirror restores — the tiers differ only
        in where the bytes come from."""
        # Re-place on the *current* mesh/shardings: the elastic-checkpoint
        # capability (reference stage1.py:1030 re-partitions for a new dp
        # world size) comes for free from resharding on load.
        if self._offload:
            # Masters come from the checkpoint's fp32 "params" entry;
            # the opt_state tree carries moments + step only.
            opt = self.cpu_optimizer
            flat_leaves = jax.tree_util.tree_leaves(restored["params"])
            if len(flat_leaves) != len(opt.sizes):
                raise ValueError(
                    f"checkpoint has {len(flat_leaves)} param leaves but "
                    f"offload optimizer expects {len(opt.sizes)}; "
                    "checkpoint is from a different model")
            for i, (leaf, off, size) in enumerate(
                    zip(flat_leaves, opt.offsets, opt.sizes)):
                if int(np.size(leaf)) != int(size):
                    raise ValueError(
                        f"checkpoint param leaf {i} has {np.size(leaf)} "
                        f"elements, expected {size}; checkpoint is from a "
                        "different model shape")
                opt.master[off:off + size] = np.asarray(
                    leaf, np.float32).reshape(-1)
            if load_optimizer_states:
                saved = restored["opt_state"]
                opt.exp_avg[:] = np.asarray(saved["exp_avg"],
                                            np.float32).reshape(-1)
                opt.exp_avg_sq[:] = np.asarray(saved["exp_avg_sq"],
                                               np.float32).reshape(-1)
                opt._step = int(saved["step"])
            self.params = self._upload_offload_params()
        else:
            # Streaming placement: each leaf is device_put individually and
            # its host copy dropped immediately after, so peak host memory
            # during an (elastic) restore stays ~one full section + one
            # leaf rather than the whole state tree twice.
            self.params = stream_device_put(
                self._reshape_for_restage(restored["params"], self.params,
                                          "param"),
                self._shardings["param"])
            del restored["params"]
            if load_optimizer_states:
                opt_tree = restored.pop("opt_state")
                opt_tree["m"] = self._reshape_for_restage(
                    opt_tree["m"], self.opt_state.m, "opt.m")
                opt_tree["v"] = self._reshape_for_restage(
                    opt_tree["v"], self.opt_state.v, "opt.v")
                self.opt_state = stream_device_put(
                    self._opt_state_from_tree(opt_tree, self.opt_state),
                    self._opt_state_shardings())
        ds = restored["device_state"]
        self.device_state = jax.device_put(
            DeviceState(
                loss_scale=LossScaleState(
                    cur_scale=jnp.asarray(ds["cur_scale"], jnp.float32),
                    cur_iter=jnp.asarray(ds["cur_iter"], jnp.int32),
                    last_overflow_iter=jnp.asarray(ds["last_overflow_iter"],
                                                   jnp.int32),
                    cur_hysteresis=jnp.asarray(ds["cur_hysteresis"],
                                               jnp.int32)),
                global_step=jnp.asarray(ds["global_step"], jnp.int32),
                skipped_steps=jnp.asarray(ds["skipped_steps"], jnp.int32),
                # Absent in checkpoints saved before the resilience PR.
                consecutive_skipped=jnp.asarray(
                    ds.get("consecutive_skipped", 0), jnp.int32)),
            NamedSharding(self.mesh, PartitionSpec()))

        self.global_steps = meta["global_steps"]
        self.micro_steps = meta["micro_steps"]
        if meta.get("rng_base_key") is not None:
            self._rng = jnp.asarray(meta["rng_base_key"],
                                    np.asarray(self._rng).dtype)
        if load_lr_scheduler_states and meta.get("lr_scheduler") and \
                self.lr_scheduler is not None and \
                hasattr(self.lr_scheduler, "load_state_dict"):
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        if meta.get("dataloader") is not None and self._data_iter is not None:
            self._data_iter.load_state_dict(meta["dataloader"])
        if self._health_monitor is not None:
            # Pre-restore loss history would poison the spike detector.
            self._health_monitor.reset_history()

    def _hot_snapshot(self):
        """One in-RAM hot snapshot (async CRC stamp + optional mirror)."""
        t0 = time.perf_counter()
        tag = f"step{self.global_steps}"
        self._hot_store.snapshot(tag, self._checkpoint_state_tree(),
                                 self._checkpoint_meta(None),
                                 topology=self._topology())
        if self.telemetry is not None:
            self.telemetry.emit(
                "hot_snapshot", step=self.global_steps, tag=tag,
                mirrored=bool(self._hot_store.mirror_dir),
                duration_s=round(time.perf_counter() - t0, 6))

    def _install_hot_restore(self, got, tier):
        """Install a hot-tier ``(state, meta, topology)`` triple; False
        when the snapshot's topology fingerprint no longer matches (a
        restart onto a different mesh must fall through to the disk
        tier, whose elastic reshard-on-load can absorb the change)."""
        state, meta, topology = got
        try:
            check = check_topology(topology, self._topology(),
                                   elastic=False)
        except Exception as e:
            logger.warning("hot restore (%s): topology check failed "
                           "(%s); falling through", tier, e)
            return False
        if check.kind != "same":
            logger.warning(
                "hot restore (%s): snapshot topology %s does not match "
                "the current mesh; falling through to disk", tier,
                check.changed if hasattr(check, "changed") else check.kind)
            return False
        self._install_restored_state(state, meta)
        return True

    def _emit_recovery(self, tier, source, t0, error=None):
        if self.telemetry is not None:
            self.telemetry.emit(
                "recovery_ladder", tier=tier, source=source,
                step=self.global_steps,
                duration_s=round(time.perf_counter() - t0, 6),
                error=error)

    def _auto_resume(self):
        """Resume through the recovery ladder: hot RAM → hot mirror →
        newest valid disk checkpoint (→ older disk, inside
        ``resolve_tag``). Returns a description of what was loaded, or
        None when nothing is loadable (fresh start). Each successful
        rung emits a ``recovery_ladder`` event naming the tier, so
        ``ds_tpu_metrics summary`` shows which tier actually served the
        restart."""
        rz = self._config.resilience
        t0 = time.perf_counter()
        if self._hot_store is not None:
            # Tier 1: hot RAM — survives in-process restarts only (a
            # fresh process starts with an empty store).
            try:
                got = self._hot_store.restore()
            except HotCheckpointCorruptError as e:
                logger.warning("hot RAM restore rejected: %s", e)
                got = None
            if got is not None and self._install_hot_restore(got,
                                                             "hot_ram"):
                self._emit_recovery("hot_ram", "<ram>", t0)
                return "<hot_ram>"
            # Tier 2: hot mirror on local disk — the fast path for a
            # restarted process. Snapshot leaves are keyed by path; the
            # fresh-init state tree supplies the structure.
            if rz.hot_mirror_dir and os.path.isdir(rz.hot_mirror_dir):
                try:
                    got = HotCheckpointStore.load_mirror(
                        rz.hot_mirror_dir, self._checkpoint_state_tree())
                except HotCheckpointCorruptError as e:
                    logger.warning("hot mirror restore rejected: %s", e)
                    got = None
                if got is not None and self._install_hot_restore(
                        got, "hot_mirror"):
                    self._emit_recovery("hot_mirror", rz.hot_mirror_dir,
                                        t0)
                    return f"<hot_mirror:{rz.hot_mirror_dir}>"
        # Tier 3: durable disk checkpoints (resolve_tag already scans
        # past a corrupt newest one, emitting checkpoint_fallback).
        tag = self._ckpt_manager.resolve_tag(rz.save_dir, None)
        if tag is None:
            return None
        path, _ = self.load_checkpoint(rz.save_dir)
        if path is not None:
            self._emit_recovery("disk", str(path), t0)
        return path
