"""Learning-rate schedules: LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR.

TPU-native analog of the reference's ``deepspeed/runtime/lr_schedules.py``
(classes at `runtime/lr_schedules.py:301,401,645,722`). The semantics are the
same, but each schedule's math lives in a pure ``lr_at(step)`` usable both
eagerly (Python floats) and under ``jax.jit`` (traced step counters), so the
engine can fold the schedule into the compiled train step instead of mutating
param-group state between steps.
"""

import argparse

import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

EDGE_VALUE = "edge_value"
MID_VALUE = "mid_value"

CYCLE_FIRST_STEP_SIZE = "cycle_first_step_size"
CYCLE_FIRST_STAIR_COUNT = "cycle_first_stair_count"
CYCLE_SECOND_STEP_SIZE = "cycle_second_step_size"
CYCLE_SECOND_STAIR_COUNT = "cycle_second_stair_count"
DECAY_STEP_SIZE = "decay_step_size"

CYCLE_MIN_LR = "cycle_min_lr"
CYCLE_MAX_LR = "cycle_max_lr"
DECAY_LR_RATE = "decay_lr_rate"

CYCLE_MIN_MOM = "cycle_min_mom"
CYCLE_MAX_MOM = "cycle_max_mom"
DECAY_MOM_RATE = "decay_mom_rate"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"

TOTAL_NUM_STEPS = "total_num_steps"


def add_tuning_arguments(parser):
    """CLI LR-tuning argument group (reference: `lr_schedules.py:54-152`)."""
    group = parser.add_argument_group("Convergence Tuning",
                                      "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    return parser


def parse_arguments():
    parser = argparse.ArgumentParser()
    parser = add_tuning_arguments(parser)
    lr_sched_args, unknown_args = parser.parse_known_args()
    return lr_sched_args, unknown_args


class _Schedule:
    """Base: stateful step API around a pure per-step lr computation."""

    def __init__(self, last_batch_iteration=-1):
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, step):
        raise NotImplementedError

    def as_fn(self):
        """Pure ``step -> lr`` function for folding into a jitted train step."""
        return self.lr_at

    def get_lr(self):
        if self.last_batch_iteration < 0:
            logger.warning("Attempting to get learning rate from scheduler "
                           "before it has started")
            return [0.0]
        return [float(self.lr_at(self.last_batch_iteration))]

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_Schedule):
    """LR range test: lr = min_lr * (1 + step_rate * interval(step))."""

    def __init__(self,
                 lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000,
                 lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False,
                 last_batch_iteration=-1,
                 optimizer=None):
        super().__init__(last_batch_iteration)
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def lr_at(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        interval = jnp.floor(step / self.step_size) if self.staircase \
            else step / self.step_size
        return self.min_lr * (1 + self.step_rate * interval)


class OneCycle(_Schedule):
    """1-cycle policy: triangular lr cycle then post-cycle decay.

    Momentum cycling is exposed via ``mom_at(step)`` (the reference mutates
    optimizer betas as a side effect; here the engine folds the momentum
    schedule into the jitted optimizer update).
    """

    def __init__(self,
                 cycle_min_lr,
                 cycle_max_lr,
                 decay_lr_rate=0.0,
                 cycle_first_step_size=2000,
                 cycle_second_step_size=None,
                 cycle_first_stair_count=0,
                 cycle_second_stair_count=None,
                 decay_step_size=0,
                 cycle_momentum=True,
                 cycle_min_mom=0.8,
                 cycle_max_mom=0.9,
                 decay_mom_rate=0.0,
                 last_batch_iteration=-1,
                 optimizer=None):
        super().__init__(last_batch_iteration)
        first = float(cycle_first_step_size)
        second = float(cycle_second_step_size) \
            if cycle_second_step_size is not None else first
        self.total_size = first + second
        self.step_ratio = first / self.total_size
        self.first_stair_count = cycle_first_stair_count
        self.second_stair_count = cycle_first_stair_count \
            if cycle_second_stair_count is None else cycle_second_stair_count
        self.decay_step_size = decay_step_size
        self.min_lr = cycle_min_lr
        self.max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.cycle_momentum = cycle_momentum
        self.min_mom = cycle_min_mom
        self.max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def _scale_factor(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        cycle = jnp.floor(1 + step / self.total_size)
        x = 1.0 + step / self.total_size - cycle
        return jnp.where(x <= self.step_ratio,
                         x / self.step_ratio,
                         (x - 1) / (self.step_ratio - 1))

    def _decay_interval(self, step):
        decay_steps = jnp.asarray(step, jnp.float32) - self.total_size
        return decay_steps / max(self.decay_step_size, 1)

    def lr_at(self, step):
        cycle_lr = self.min_lr + (self.max_lr - self.min_lr) * self._scale_factor(step)
        decay_lr = self.min_lr * (1 + self.decay_lr_rate * self._decay_interval(step))
        in_cycle = jnp.asarray(step, jnp.float32) <= self.total_size
        return jnp.where(in_cycle, cycle_lr, decay_lr)

    def mom_at(self, step):
        cycle_mom = self.max_mom - (self.max_mom - self.min_mom) * self._scale_factor(step)
        decay_mom = self.max_mom * (1 + self.decay_mom_rate * self._decay_interval(step))
        in_cycle = jnp.asarray(step, jnp.float32) <= self.total_size
        return jnp.where(in_cycle, cycle_mom, decay_mom)

    def get_mom(self):
        if not self.cycle_momentum:
            return None
        return [(float(self.mom_at(max(self.last_batch_iteration, 0))), 0.99)]


class WarmupLR(_Schedule):
    """Log-warmup from min_lr to max_lr over warmup_num_steps, then flat."""

    def __init__(self,
                 warmup_min_lr=0.0,
                 warmup_max_lr=0.001,
                 warmup_num_steps=1000,
                 last_batch_iteration=-1,
                 optimizer=None):
        super().__init__(last_batch_iteration)
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.delta_lr = warmup_max_lr - warmup_min_lr
        self.warmup_num_steps = warmup_num_steps
        self.inverse_log_warm_up = 1.0 / jnp.log(float(warmup_num_steps))

    def _gamma(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        warm = self.inverse_log_warm_up * jnp.log(step + 1)
        return jnp.where(step < self.warmup_num_steps, warm, 1.0)

    def lr_at(self, step):
        return self.min_lr + self.delta_lr * self._gamma(step)


class WarmupDecayLR(WarmupLR):
    """Log-warmup then linear decay to zero at total_num_steps."""

    def __init__(self,
                 total_num_steps,
                 warmup_min_lr=0.0,
                 warmup_max_lr=0.001,
                 warmup_num_steps=1000,
                 last_batch_iteration=-1,
                 optimizer=None):
        self.total_num_steps = total_num_steps
        super().__init__(warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            logger.warning(
                "total_num_steps {} is less than warmup_num_steps {}".format(
                    total_num_steps, warmup_num_steps))

    def _gamma(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        warm = self.inverse_log_warm_up * jnp.log(step + 1)
        decay = jnp.maximum(
            0.0, (self.total_num_steps - step) /
            max(1.0, self.total_num_steps - self.warmup_num_steps))
        return jnp.where(step < self.warmup_num_steps, warm, decay)


SCHEDULE_REGISTRY = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}


def get_lr_scheduler(name, params):
    """Instantiate a schedule by config name (engine resolver analog)."""
    if name not in SCHEDULE_REGISTRY:
        raise ValueError(f"unknown lr schedule {name}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_REGISTRY[name](**params)
