"""Runtime helper math: overflow checks, norms, partitioning, sharded tensors.

TPU-native analog of the reference's ``deepspeed/runtime/utils.py``:
``CheckOverflow`` (:41), ``get_grad_norm`` (:154), ``partition_uniform`` (:295),
``partition_balanced`` (:361), ``PartitionedTensor`` (:379),
``see_memory_usage`` (:531). Overflow checks and norms are pure jnp functions
(jit-safe, mesh-aware via an optional ``axis_name`` when called inside
``shard_map``); partitioning is plain Python (it runs at trace/setup time).
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------------------
# Overflow detection (reference: CheckOverflow / _has_inf_or_nan)
# ---------------------------------------------------------------------------

def has_inf_or_nan(x):
    """True iff any element of ``x`` is inf or nan. jit-safe; returns a
    traced boolean scalar."""
    return jnp.logical_not(jnp.all(jnp.isfinite(x.astype(jnp.float32))))


def check_overflow(grads, axis_names=()):
    """Overflow vote over a grad pytree.

    Inside ``shard_map``, pass the mesh axis names to reduce the vote across
    shards — the analog of the reference's MAX-allreduce overflow vote across
    dp and mp groups (`zero/stage2.py:1527-1551`).
    """
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        overflow = jnp.asarray(False)
    else:
        flags = [has_inf_or_nan(g) for g in leaves]
        overflow = jnp.any(jnp.stack(flags))
    for axis in axis_names:
        overflow = jax.lax.pmax(overflow.astype(jnp.int32), axis) > 0
    return overflow


class CheckOverflow:
    """Stateful facade over ``check_overflow`` for engine parity."""

    def __init__(self, param_groups=None, mpu=None, zero_reduce_scatter=False):
        self.mpu = mpu
        self.zero_reduce_scatter = zero_reduce_scatter

    def check_using_norm(self, norm_group):
        overflow = any(float(norm) in (float("inf"), -float("inf")) or
                       norm != norm for norm in norm_group)
        return overflow

    def has_overflow(self, grads):
        return bool(check_overflow(grads))


# ---------------------------------------------------------------------------
# Norms and clipping (reference: get_grad_norm / get_weight_norm / clip_grad_norm_)
# ---------------------------------------------------------------------------

def global_norm(tree, axis_names=()):
    """Global L2 norm of a pytree. ``axis_names`` psums the squared sum across
    mesh axes when shards hold disjoint slices (ZeRO / model parallel)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    for axis in axis_names:
        sq = jax.lax.psum(sq, axis)
    return jnp.sqrt(sq)


def clip_by_global_norm(tree, max_norm, norm=None, eps=1e-6):
    """Scale the pytree so its global norm is at most ``max_norm``.

    Matches the reference's clip: scale = max_norm / (norm + eps) applied only
    when norm exceeds max_norm.
    """
    if norm is None:
        norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + eps))
    return jax.tree_util.tree_map(lambda x: (x * scale).astype(x.dtype), tree)


def get_grad_norm(gradients, axis_names=()):
    return global_norm(gradients, axis_names)


def get_weight_norm(parameters, axis_names=()):
    return global_norm(parameters, axis_names)


# ---------------------------------------------------------------------------
# Partitioning math (reference: partition_uniform :295, partition_balanced :361)
# Pure Python — used for pipeline stage assignment and ZeRO bookkeeping,
# runs at setup time, unit-testable without devices.
# ---------------------------------------------------------------------------

def prefix_sum_inc(weights):
    """Inclusive prefix sum of a list."""
    out = []
    total = 0
    for w in weights:
        total += w
        out.append(total)
    return out


def partition_uniform(num_items, num_parts):
    """Even split boundaries: len == num_parts+1, remainder spread across
    the leading parts."""
    parts = [(p * num_items) // num_parts for p in range(num_parts)]
    parts.append(num_items)
    return parts


def _feasible(weights, num_parts, bottleneck):
    """Greedy check: can ``weights`` split into ≤ num_parts contiguous chunks
    each with sum ≤ bottleneck?"""
    parts_used = 1
    current = 0
    for w in weights:
        if w > bottleneck:
            return False
        if current + w > bottleneck:
            parts_used += 1
            current = w
            if parts_used > num_parts:
                return False
        else:
            current += w
    return True


def partition_balanced(weights, num_parts, eps=1e-3):
    """Boundaries minimizing the max part weight (contiguous partition).

    Same capability as the reference's binary-search-over-prefix-sums
    (`runtime/utils.py:361,310`): binary search the bottleneck, then lay out
    chunks greedily while keeping every trailing part non-empty.
    """
    num_items = len(weights)
    if num_items <= num_parts:
        # Degenerate: one item (or empty) per part.
        parts = list(range(num_items + 1))
        parts += [num_items] * (num_parts - num_items)
        return parts

    lo = max(weights) if weights else 0
    hi = sum(weights)
    while lo < hi:
        mid = (lo + hi) // 2 if isinstance(lo, int) and isinstance(hi, int) \
            else (lo + hi) / 2
        if _feasible(weights, num_parts, mid):
            hi = mid
        else:
            lo = mid + 1 if isinstance(mid, int) else mid + eps
    bottleneck = hi

    # Greedy layout, reserving enough items for the remaining parts.
    parts = [0]
    idx = 0
    for p in range(num_parts):
        remaining_parts = num_parts - p - 1
        current = 0
        while idx < num_items - remaining_parts:
            if current + weights[idx] > bottleneck and current > 0:
                break
            current += weights[idx]
            idx += 1
        parts.append(idx)
    parts[-1] = num_items
    return parts


# ---------------------------------------------------------------------------
# PartitionedTensor (reference: runtime/utils.py:379-486)
# ---------------------------------------------------------------------------

class PartitionedTensor:
    """A tensor flattened, padded, and split into ``world`` equal shards.

    The reference version shards over a process group and reconstructs with an
    allgather; here the shards are plain arrays plus meta, and ``full()``
    reconstruction is a concatenate (per-host) or an ``all_gather`` when used
    inside ``shard_map`` via :func:`from_shard`.
    """

    def __init__(self, tensor, world, rank=None):
        self.orig_shape = tuple(tensor.shape)
        self.orig_dtype = tensor.dtype
        self.world = world
        flat = tensor.reshape(-1)
        self.orig_size = flat.shape[0]
        pad = (-self.orig_size) % world
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        self.padded_size = flat.shape[0]
        self._shards = flat.reshape(world, -1)
        self.rank = rank

    def local_data(self, rank=None):
        r = self.rank if rank is None else rank
        assert r is not None, "rank required to read a local shard"
        return self._shards[r]

    def to_meta(self):
        return {
            "orig_shape": self.orig_shape,
            "orig_size": self.orig_size,
            "padded_size": self.padded_size,
            "world": self.world,
            "dtype": self.orig_dtype,
        }

    @staticmethod
    def full_from_shards(shards, meta):
        """Rebuild the original tensor from stacked shards [world, shard]."""
        flat = shards.reshape(-1)[: meta["orig_size"]]
        return flat.reshape(meta["orig_shape"]).astype(meta["dtype"])

    @staticmethod
    def full_from_local(shard, meta, axis_name):
        """Inside shard_map: allgather this rank's shard along ``axis_name``
        and rebuild (the reference's dist.all_gather path)."""
        gathered = jax.lax.all_gather(shard, axis_name)
        return PartitionedTensor.full_from_shards(gathered, meta)

    def full(self):
        return self.full_from_shards(self._shards, self.to_meta())


# ---------------------------------------------------------------------------
# Memory reporting (reference: see_memory_usage :531)
# ---------------------------------------------------------------------------

def see_memory_usage(message, force=False):
    try:
        parts = []
        for d in jax.local_devices():
            stats = d.memory_stats() or {}
            parts.append(
                f"{d.platform}:{d.id} in_use "
                f"{stats.get('bytes_in_use', 0) / 2**30:.2f}GB peak "
                f"{stats.get('peak_bytes_in_use', 0) / 2**30:.2f}GB")
        logger.info(f"{message} | {' | '.join(parts)}")
    except Exception:
        logger.info(f"{message} | memory stats unavailable")
