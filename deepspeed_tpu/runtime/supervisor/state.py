"""Worker-slot bookkeeping for the ``ds_tpu_run`` supervisor.

A *slot* is a logical process index in the current (possibly downsized)
world; the OS process occupying it changes across restarts. The
supervisor classifies every failure into one of the causes below — the
cause drives both the telemetry (``restart`` events, restart counters
by cause) and the policy (repeated failures of the same slot trigger an
elastic downsize).
"""

import time
from typing import NamedTuple

# Failure causes (the `cause` field of restart events).
CAUSE_CRASH = "crash"            # nonzero/negative exit code
CAUSE_HANG = "hang"              # heartbeat shows a stuck step
CAUSE_PREEMPTION = "preemption"  # clean exit 0 without a done marker

# Terminal reasons (SupervisorResult.reason).
REASON_COMPLETED = "completed"
REASON_RESTART_BUDGET = "restart_budget_exhausted"


class SupervisorResult(NamedTuple):
    """What one supervised job run amounted to."""
    success: bool
    reason: str
    restarts: int
    downsizes: int
    world_size: int
    causes: dict     # cause -> count


class WorkerSlot:
    """One logical worker: index, live process, failure history."""

    def __init__(self, index):
        self.index = int(index)
        self.proc = None
        self.started_t = None
        self.attempt = 0               # spawns of this slot so far
        self.consecutive_failures = 0  # reset on any observed progress
        self.done = False
        self.last_step = None          # newest heartbeat step seen

    @property
    def running(self):
        return self.proc is not None and self.proc.poll() is None

    @property
    def pid(self):
        return self.proc.pid if self.proc is not None else None

    def mark_spawned(self, proc, clock=time.monotonic):
        self.proc = proc
        self.started_t = clock()
        self.attempt += 1

    def __repr__(self):
        state = "done" if self.done else \
            ("running" if self.running else "down")
        return (f"WorkerSlot(index={self.index}, {state}, "
                f"pid={self.pid}, attempt={self.attempt}, "
                f"consecutive_failures={self.consecutive_failures})")
