"""The ``ds_tpu_run`` supervisor: spawn, watch, classify, restart.

One :class:`Supervisor` owns a job of ``num_workers`` worker processes
(one per training process). It watches two signals the workers already
produce — exit codes, and the hang watchdog's per-process heartbeat
files (``hb-p<idx>.json``, `telemetry/watchdog.py`) matched to workers
by pid — classifies every failure as **crash** (nonzero exit), **hang**
(heartbeat stuck in a step past ``hang_timeout_s``, or gone stale), or
**preemption** (clean exit 0 without the worker's done marker), and
recovers with a coordinated kill-and-restart: SIGTERM (letting healthy
workers take their preemption save), then SIGKILL after a grace period,
exponential backoff, respawn.

Two budgets bound the loop: ``max_restarts`` total, and — when the SAME
slot keeps failing ``downsize_after`` times in a row (a bad host, not a
bad step) — an **elastic downsize**: the job restarts with one fewer
worker, ``solve_elastic_batch`` re-derives the micro×accum plan for the
smaller world (exported to workers via ``DS_TPU_RUN_MICRO_BATCH`` /
``DS_TPU_RUN_GRAD_ACCUM`` / ``DS_TPU_RUN_LR_SCALE``), and the engine's
reshard-on-resume absorbs the topology change at checkpoint load.

Worker contract (all optional beyond the index variables):

- ``DS_TPU_RUN_PROCESS_INDEX`` / ``DS_TPU_RUN_NUM_WORKERS`` — this
  worker's slot and the current world size.
- ``DS_TPU_RUN_RESTART_COUNT`` — job-level restart count (0 first
  launch); fault-injection harnesses arm faults only when it is 0.
- ``DS_TPU_RUN_ATTEMPT`` — this slot's spawn count (1-based).
- ``DS_TPU_RUN_WORKDIR`` — the supervisor's working directory.
- On clean completion the worker must create
  ``<workdir>/done-p<idx>`` (see :func:`done_path`); exit 0 without it
  reads as a preemption and is restarted.

The supervisor emits its own telemetry (``restart`` events — durable,
fsynced — plus ``restarts_total`` counters and a ``time_to_recover``
histogram) to ``jsonl_path``, so ``ds_tpu_metrics summary`` on that log
shows the whole recovery loop.
"""

import json
import os
import signal
import subprocess
import sys
import time

from deepspeed_tpu.runtime.elastic.batch import solve_elastic_batch
from deepspeed_tpu.runtime.supervisor.state import (
    CAUSE_CRASH,
    CAUSE_HANG,
    CAUSE_PREEMPTION,
    REASON_COMPLETED,
    REASON_RESTART_BUDGET,
    SupervisorResult,
    WorkerSlot,
)
from deepspeed_tpu.utils.logging import logger

REASON_TIMEOUT = "timeout"

_HB_PREFIX = "hb-p"


def done_path(workdir, index):
    """Path of the done marker worker ``index`` writes on completion."""
    return os.path.join(workdir, f"done-p{int(index):05d}")


def classify_exit(returncode, done_marker_exists):
    """Failure cause for an exited worker, or None.

    ``None`` while still running (``returncode is None``) or on a clean
    completion (exit 0 WITH the done marker); exit 0 without the marker
    reads as :data:`CAUSE_PREEMPTION`; any nonzero exit is
    :data:`CAUSE_CRASH`. Shared by the training supervisor and the
    serving fleet router (`inference/fleet.py`) so both sides of the
    house classify process death identically."""
    if returncode is None:
        return None
    if returncode == 0 and done_marker_exists:
        return None
    return CAUSE_PREEMPTION if returncode == 0 else CAUSE_CRASH


def heartbeat_verdict(hb, now, hang_timeout_s=None,
                      heartbeat_stale_s=None):
    """:data:`CAUSE_HANG` when a live process's heartbeat says it is
    stuck (``in_step`` past ``hang_timeout_s``) or has gone stale
    (last write older than ``heartbeat_stale_s``); None otherwise.
    ``hb`` is a parsed ``hb-p<idx>.json`` dict (or None = no verdict —
    a worker that has not started reporting is covered by its exit
    code, not its silence)."""
    if hb is None:
        return None
    stuck = (hang_timeout_s is not None
             and hb.get("in_step")
             and float(hb.get("step_elapsed_s") or 0.0)
             > float(hang_timeout_s))
    stale = (heartbeat_stale_s is not None
             and now - float(hb.get("t") or now)
             > float(heartbeat_stale_s))
    return CAUSE_HANG if (stuck or stale) else None


class Supervisor:
    def __init__(self, argv, num_workers, workdir,
                 heartbeat_dir=None,
                 jsonl_path=None,
                 max_restarts=3,
                 backoff_base_s=0.5,
                 backoff_cap_s=30.0,
                 hang_timeout_s=None,
                 heartbeat_stale_s=None,
                 poll_interval_s=0.25,
                 kill_grace_s=5.0,
                 downsize_after=2,
                 min_world_size=1,
                 target_global_batch=None,
                 lr_scaling="linear",
                 timeout_s=None,
                 env=None):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, "
                             f"got {num_workers}")
        self.argv = list(argv)
        self.workdir = os.path.abspath(workdir)
        self.heartbeat_dir = os.path.abspath(heartbeat_dir) \
            if heartbeat_dir else self.workdir
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.hang_timeout_s = hang_timeout_s
        self.heartbeat_stale_s = heartbeat_stale_s
        self.poll_interval_s = float(poll_interval_s)
        self.kill_grace_s = float(kill_grace_s)
        self.downsize_after = int(downsize_after)
        self.min_world_size = max(1, int(min_world_size))
        self.target_global_batch = target_global_batch
        self.lr_scaling = lr_scaling
        self.timeout_s = timeout_s
        self.base_env = dict(env) if env is not None else dict(os.environ)

        self.world_size = int(num_workers)
        self.slots = [WorkerSlot(i) for i in range(self.world_size)]
        self.restarts = 0
        self.downsizes = 0
        self.causes = {}
        self._session = None
        if jsonl_path:
            from deepspeed_tpu.telemetry.session import TelemetrySession
            from deepspeed_tpu.telemetry.exporters import JsonlExporter
            self._session = TelemetrySession(
                exporters=[JsonlExporter(jsonl_path)])

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _emit(self, event, **fields):
        if self._session is not None:
            try:
                self._session.emit(event, **fields)
            except Exception:   # pragma: no cover - telemetry never kills
                pass

    def _count_restart(self, cause, time_to_recover_s):
        if self._session is not None:
            reg = self._session.registry
            reg.counter("restarts_total", labels={"cause": cause},
                        help="supervisor restarts by failure cause").inc()
            reg.histogram(
                "time_to_recover_seconds",
                help="failure detection to workers respawned"
            ).observe(time_to_recover_s)

    # ------------------------------------------------------------------
    # spawning
    # ------------------------------------------------------------------
    def _batch_plan_env(self):
        if not self.target_global_batch:
            return {}
        plan = solve_elastic_batch(self.target_global_batch,
                                   self.world_size,
                                   lr_scaling=self.lr_scaling)
        return {
            "DS_TPU_RUN_MICRO_BATCH": str(plan.micro_batch),
            "DS_TPU_RUN_GRAD_ACCUM": str(plan.grad_accum),
            "DS_TPU_RUN_LR_SCALE": repr(plan.lr_scale),
        }

    def _spawn(self, slot):
        env = dict(self.base_env)
        env.update(self._batch_plan_env())
        env.update({
            "DS_TPU_RUN_PROCESS_INDEX": str(slot.index),
            "DS_TPU_RUN_NUM_WORKERS": str(self.world_size),
            "DS_TPU_RUN_RESTART_COUNT": str(self.restarts),
            "DS_TPU_RUN_ATTEMPT": str(slot.attempt + 1),
            "DS_TPU_RUN_WORKDIR": self.workdir,
        })
        log_dir = os.path.join(self.workdir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_f = open(os.path.join(log_dir, f"w{slot.index}.log"), "ab")
        try:
            proc = subprocess.Popen(self.argv, env=env, stdout=log_f,
                                    stderr=subprocess.STDOUT,
                                    cwd=self.workdir)
        finally:
            log_f.close()   # the child holds its own fd
        slot.mark_spawned(proc)
        slot.last_step = None
        logger.info("ds_tpu_run: spawned worker %d (pid %d, attempt %d, "
                    "world %d)", slot.index, proc.pid, slot.attempt,
                    self.world_size)

    def _spawn_all(self):
        for slot in self.slots:
            if not slot.done:
                self._spawn(slot)

    # ------------------------------------------------------------------
    # monitoring
    # ------------------------------------------------------------------
    def _scan_heartbeats(self):
        """pid -> newest parseable heartbeat under heartbeat_dir (walked
        recursively: per-worker crash dirs nest in CPU test mode, one
        shared dir on a real pod)."""
        out = {}
        for dirpath, _, filenames in os.walk(self.heartbeat_dir):
            for name in filenames:
                if not (name.startswith(_HB_PREFIX)
                        and name.endswith(".json")):
                    continue
                try:
                    with open(os.path.join(dirpath, name)) as f:
                        hb = json.load(f)
                except (OSError, ValueError):
                    continue
                if isinstance(hb, dict) and hb.get("pid") is not None:
                    prev = out.get(int(hb["pid"]))
                    if prev is None or hb.get("t", 0) > prev.get("t", 0):
                        out[int(hb["pid"])] = hb
        return out

    def _classify_failure(self):
        """(cause, slot) of the first detected failure, or (None, None).
        Also flips ``done`` on slots whose marker appeared and resets
        failure streaks on observed step progress."""
        heartbeats = self._scan_heartbeats()
        now = time.time()
        for slot in self.slots:
            if slot.done:
                continue
            rc = slot.proc.poll() if slot.proc is not None else None
            if rc is not None:
                cause = classify_exit(
                    rc, os.path.exists(done_path(self.workdir,
                                                 slot.index)))
                if cause is None:
                    slot.done = True
                    logger.info("ds_tpu_run: worker %d completed",
                                slot.index)
                    continue
                return cause, slot
            hb = heartbeats.get(slot.pid)
            if hb is None:
                continue   # not started reporting yet; exit code covers
            step = hb.get("step")
            if step is not None:
                if slot.last_step is not None and step > slot.last_step:
                    slot.consecutive_failures = 0
                slot.last_step = step
            cause = heartbeat_verdict(
                hb, now, hang_timeout_s=self.hang_timeout_s,
                heartbeat_stale_s=self.heartbeat_stale_s)
            if cause is not None:
                return cause, slot
        return None, None

    # ------------------------------------------------------------------
    # kill / restart
    # ------------------------------------------------------------------
    def _kill_all(self):
        """Coordinated stop: SIGTERM everyone (healthy workers take
        their preemption save), grace period, then SIGKILL leftovers."""
        live = [s for s in self.slots if s.running]
        for slot in live:
            try:
                slot.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + self.kill_grace_s
        for slot in live:
            remaining = deadline - time.monotonic()
            try:
                slot.proc.wait(timeout=max(0.0, remaining))
            except subprocess.TimeoutExpired:
                try:
                    slot.proc.kill()
                    slot.proc.wait(timeout=self.kill_grace_s)
                except (OSError, subprocess.TimeoutExpired):
                    pass

    def _maybe_downsize(self, failed):
        """Drop ``failed``'s slot when it keeps failing and the world
        can shrink; returns True when the world changed. A downsize is a
        full job restart: done markers are cleared (the smaller world
        re-derives the batch plan, so completed work from the old plan
        no longer lines up) and every slot's history resets."""
        if failed.consecutive_failures < self.downsize_after or \
                self.world_size <= self.min_world_size:
            return False
        self.world_size -= 1
        self.downsizes += 1
        for slot in self.slots:
            marker = done_path(self.workdir, slot.index)
            if os.path.exists(marker):
                try:
                    os.remove(marker)
                except OSError:
                    pass
        self.slots = [WorkerSlot(i) for i in range(self.world_size)]
        logger.warning(
            "ds_tpu_run: worker slot %d failed %d consecutive times — "
            "elastic downsize to world %d", failed.index,
            failed.consecutive_failures, self.world_size)
        return True

    def _restart(self, cause, failed):
        t_detect = time.monotonic()
        self._kill_all()
        failed.consecutive_failures += 1
        downsized = self._maybe_downsize(failed)
        # Count the restart BEFORE respawning: workers read the updated
        # DS_TPU_RUN_RESTART_COUNT (fault harnesses arm only at 0).
        self.restarts += 1
        self.causes[cause] = self.causes.get(cause, 0) + 1
        backoff = min(self.backoff_cap_s,
                      self.backoff_base_s * (2 ** (self.restarts - 1)))
        time.sleep(backoff)
        self._spawn_all()
        time_to_recover = time.monotonic() - t_detect
        self._count_restart(cause, time_to_recover)
        self._emit("restart", cause=cause, failed_index=failed.index,
                   restarts=self.restarts, world_size=self.world_size,
                   downsize=downsized, backoff_s=round(backoff, 3),
                   time_to_recover_s=round(time_to_recover, 3),
                   consecutive_failures=failed.consecutive_failures)
        logger.warning(
            "ds_tpu_run: restart %d/%d (cause=%s, worker %d%s) after "
            "%.2fs backoff", self.restarts, self.max_restarts, cause,
            failed.index,
            ", downsized" if downsized else "", backoff)

    # ------------------------------------------------------------------
    # run loop
    # ------------------------------------------------------------------
    def run(self):
        os.makedirs(self.workdir, exist_ok=True)
        self._emit("run_start", role="supervisor",
                   num_workers=self.world_size, argv=self.argv,
                   max_restarts=self.max_restarts,
                   hang_timeout_s=self.hang_timeout_s)
        self._spawn_all()
        t0 = time.monotonic()
        try:
            while True:
                time.sleep(self.poll_interval_s)
                cause, failed = self._classify_failure()
                if cause is not None:
                    if self.restarts >= self.max_restarts:
                        self._kill_all()
                        return self._finish(False, REASON_RESTART_BUDGET,
                                            last_cause=cause)
                    self._restart(cause, failed)
                    continue
                if all(slot.done for slot in self.slots):
                    return self._finish(True, REASON_COMPLETED)
                if self.timeout_s is not None and \
                        time.monotonic() - t0 > self.timeout_s:
                    self._kill_all()
                    return self._finish(False, REASON_TIMEOUT)
        finally:
            self._kill_all()
            if self._session is not None:
                self._session.close()

    def _finish(self, success, reason, last_cause=None):
        result = SupervisorResult(
            success=success, reason=reason, restarts=self.restarts,
            downsizes=self.downsizes, world_size=self.world_size,
            causes=dict(self.causes))
        self._emit("supervisor_done", success=success, reason=reason,
                   restarts=self.restarts, downsizes=self.downsizes,
                   world_size=self.world_size, causes=self.causes,
                   last_cause=last_cause)
        (logger.info if success else logger.error)(
            "ds_tpu_run: %s (restarts=%d, downsizes=%d, world=%d)",
            reason, self.restarts, self.downsizes, self.world_size)
        return result
