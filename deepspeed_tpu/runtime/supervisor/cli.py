"""Argument parsing for ``ds_tpu_run`` (see ``bin/ds_tpu_run``).

Everything after ``--`` is the worker command, spawned once per
process index::

    ds_tpu_run --nproc 2 --workdir /tmp/job \\
        --hang-timeout-s 30 --max-restarts 3 \\
        -- python train.py --config ds_config.json

Exit status: 0 when every worker completed (wrote its done marker),
1 otherwise (restart budget exhausted, or --timeout-s hit).
"""

import argparse
import sys

from deepspeed_tpu.runtime.supervisor.supervisor import Supervisor


def build_parser():
    p = argparse.ArgumentParser(
        prog="ds_tpu_run",
        description="Launch and supervise a deepspeed_tpu job: restart "
                    "on crash/hang/preemption, downsize on repeated "
                    "failure. Worker command follows `--`.")
    p.add_argument("--nproc", type=int, required=True,
                   help="number of worker processes to launch")
    p.add_argument("--workdir", required=True,
                   help="job working directory (worker cwd, logs/, "
                        "done markers)")
    p.add_argument("--heartbeat-dir", default=None,
                   help="directory scanned (recursively) for the "
                        "watchdog's hb-p*.json files; default: workdir")
    p.add_argument("--jsonl", default=None,
                   help="supervisor telemetry JSONL log (restart / "
                        "recovery events for ds_tpu_metrics)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="job-level restart budget (default 3)")
    p.add_argument("--backoff-base-s", type=float, default=0.5,
                   help="first restart backoff; doubles per restart "
                        "(default 0.5)")
    p.add_argument("--backoff-cap-s", type=float, default=30.0,
                   help="backoff ceiling in seconds (default 30)")
    p.add_argument("--hang-timeout-s", type=float, default=None,
                   help="declare a hang when a heartbeat reports "
                        "in_step with step_elapsed_s past this "
                        "(default: off)")
    p.add_argument("--heartbeat-stale-s", type=float, default=None,
                   help="declare a hang when a worker's heartbeat file "
                        "stops updating for this long (default: off)")
    p.add_argument("--poll-interval-s", type=float, default=0.25,
                   help="supervisor poll period (default 0.25)")
    p.add_argument("--kill-grace-s", type=float, default=5.0,
                   help="SIGTERM to SIGKILL grace on coordinated stop "
                        "(default 5)")
    p.add_argument("--downsize-after", type=int, default=2,
                   help="consecutive failures of one slot before an "
                        "elastic downsize (default 2)")
    p.add_argument("--min-world", type=int, default=1,
                   help="never downsize below this world size "
                        "(default 1)")
    p.add_argument("--target-global-batch", type=int, default=None,
                   help="re-solve micro/accum for the current world "
                        "and export DS_TPU_RUN_MICRO_BATCH / "
                        "_GRAD_ACCUM / _LR_SCALE to workers")
    p.add_argument("--lr-scaling", default="linear",
                   choices=("linear", "sqrt", "none"),
                   help="LR rescale rule for elastic batch plans "
                        "(default linear)")
    p.add_argument("--timeout-s", type=float, default=None,
                   help="abort the whole job after this long "
                        "(default: none)")
    p.add_argument("worker_cmd", nargs=argparse.REMAINDER,
                   help="worker command after `--`")
    return p


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    cmd = list(args.worker_cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no worker command given (append `-- cmd ...`)")
    sup = Supervisor(
        cmd, args.nproc, args.workdir,
        heartbeat_dir=args.heartbeat_dir,
        jsonl_path=args.jsonl,
        max_restarts=args.max_restarts,
        backoff_base_s=args.backoff_base_s,
        backoff_cap_s=args.backoff_cap_s,
        hang_timeout_s=args.hang_timeout_s,
        heartbeat_stale_s=args.heartbeat_stale_s,
        poll_interval_s=args.poll_interval_s,
        kill_grace_s=args.kill_grace_s,
        downsize_after=args.downsize_after,
        min_world_size=args.min_world,
        target_global_batch=args.target_global_batch,
        lr_scaling=args.lr_scaling,
        timeout_s=args.timeout_s,
    )
    result = sup.run()
    print(f"ds_tpu_run: {result.reason} "
          f"(restarts={result.restarts}, downsizes={result.downsizes}, "
          f"world={result.world_size}, causes={result.causes})",
          file=sys.stderr)
    return 0 if result.success else 1


if __name__ == "__main__":
    sys.exit(main())
