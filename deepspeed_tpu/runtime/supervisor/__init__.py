"""Job-level recovery: the ``ds_tpu_run`` launcher/supervisor.

Per-process resilience (PRs 2/12: guards, preemption saves, watchdog,
flight recorder) detects failures but cannot act on them — a hung or
crashed worker dumps its black box and dies. The supervisor closes the
detect→recover loop at the *job* level:

- :mod:`state` — worker-slot bookkeeping, failure causes, the
  supervisor result type.
- :mod:`supervisor` — :class:`Supervisor`: spawns the per-process
  workers, watches exit codes and the watchdog heartbeat files
  (``hb-p<idx>.json``), classifies failures (crash / hang / preemption),
  and performs coordinated kill-and-restart with exponential backoff, a
  max-restart budget, and elastic downsizing when the same slot keeps
  failing (``solve_elastic_batch`` re-derives the batch plan; the
  engine's reshard-on-resume absorbs the world-size change on load).
- :mod:`cli` — the ``ds_tpu_run`` command line (``bin/ds_tpu_run``).

Restart/recovery telemetry lands in the supervisor's own JSONL log
(``restart`` events, restart counters, a time-to-recover histogram) so
``ds_tpu_metrics summary`` sees the whole loop.
"""

from deepspeed_tpu.runtime.supervisor.state import (
    CAUSE_CRASH,
    CAUSE_HANG,
    CAUSE_PREEMPTION,
    SupervisorResult,
    WorkerSlot,
)
from deepspeed_tpu.runtime.supervisor.supervisor import Supervisor

__all__ = [
    "CAUSE_CRASH",
    "CAUSE_HANG",
    "CAUSE_PREEMPTION",
    "Supervisor",
    "SupervisorResult",
    "WorkerSlot",
]
