"""Activation checkpointing (rematerialization), TPU-native.

Capability parity with the reference's Megatron-compatible checkpointing
(`runtime/activation_checkpointing/checkpointing.py:325-576,579,654`), with
the mechanisms re-designed for XLA:

- ``CheckpointFunction`` (autograd.Function saving inputs, replaying RNG
  states in backward) becomes ``jax.checkpoint``: XLA rematerializes the
  segment inside one compiled backward, and RNG replay is free because JAX
  PRNG keys are explicit values — the same key threads through both the
  forward and the rematerialized forward, so dropout patterns match by
  construction. The whole ``CudaRNGStatesTracker`` / ``_CUDA_RNG_STATE_
  TRACKER`` fork/restore machinery (reference 147-278) collapses into
  :class:`RNGKeyTracker`, a deterministic named-key derivation helper.
- ``partition_activations`` (reference 369-397: each MP rank stores 1/mp of
  every saved activation, allgathered back in backward at 281-322) becomes a
  sharding constraint over the ``model`` mesh axis on the checkpointed
  inputs — GSPMD stores the shard and inserts the all-gather.
- ``cpu_checkpointing`` (reference 410-419) becomes an offload checkpoint
  policy moving saved residuals to pinned host memory when the backend
  supports it.
- ``contiguous_memory_optimization`` (reference 398-409: preallocated
  contiguous checkpoint buffers) is subsumed by XLA's static buffer
  allocation — accepted and recorded for config parity, nothing to do.
- ``number_checkpoints`` feeds :func:`checkpoint_sequential` segmenting.
- PROFILE/SYNCHRONIZE knobs map to named-timer instrumentation around the
  checkpointed call (reference 331-335).

Public surface mirrors the reference module: ``configure``,
``is_configured``, ``checkpoint``, ``model_parallel_seed`` (analog of
``model_parallel_cuda_manual_seed``, reference 223), ``get_rng_tracker``
(analog of ``get_cuda_rng_tracker``, reference 265), ``reset``.
"""

import contextlib
import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.activation_checkpointing.config import (
    DeepSpeedActivationCheckpointingConfig)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.telemetry.timers import SynchronizedWallClockTimer

__all__ = [
    "configure", "is_configured", "reset", "checkpoint",
    "checkpoint_sequential", "make_policy", "RNGKeyTracker",
    "get_rng_tracker", "model_parallel_seed",
]

# ---------------------------------------------------------------------------
# Module-level configuration (the reference keeps the same globals,
# checkpointing.py:90-130).
# ---------------------------------------------------------------------------

_config: Optional[DeepSpeedActivationCheckpointingConfig] = None
_timers: Optional[SynchronizedWallClockTimer] = None


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Configure the module, from a DeepSpeedConfig or explicit kwargs
    (reference ``configure``, checkpointing.py:654-734)."""
    import copy
    global _config, _timers
    if deepspeed_config is not None:
        cfg = getattr(deepspeed_config, "activation_checkpointing_config",
                      None)
        if cfg is None:
            cfg = DeepSpeedActivationCheckpointingConfig(
                deepspeed_config if isinstance(deepspeed_config, dict) else {})
        else:
            # Never mutate the caller's DeepSpeedConfig sub-object — kwarg
            # overrides apply to this module's copy only.
            cfg = copy.copy(cfg)
    else:
        cfg = DeepSpeedActivationCheckpointingConfig({})
    if partition_activations is not None:
        cfg.partition_activations = partition_activations
    if contiguous_checkpointing is not None:
        cfg.contiguous_memory_optimization = contiguous_checkpointing
    if num_checkpoints is not None:
        cfg.number_checkpoints = num_checkpoints
    if checkpoint_in_cpu is not None:
        cfg.cpu_checkpointing = checkpoint_in_cpu
    if synchronize is not None:
        cfg.synchronize_checkpoint_boundary = synchronize
    if profile is not None:
        cfg.profile = profile
    _config = cfg
    if cfg.profile and _timers is None:
        _timers = SynchronizedWallClockTimer()
    return cfg


def is_configured():
    """Reference ``is_configured`` (checkpointing.py:744)."""
    return _config is not None


def reset():
    """Drop module configuration and RNG tracker state (reference ``reset``,
    checkpointing.py:246 resets the tracker; here both)."""
    global _config, _timers
    _config = None
    _timers = None
    _RNG_TRACKER.reset()


def _cfg() -> DeepSpeedActivationCheckpointingConfig:
    return _config if _config is not None else \
        DeepSpeedActivationCheckpointingConfig({})


# ---------------------------------------------------------------------------
# Checkpoint policies
# ---------------------------------------------------------------------------

def _offload_policy():
    """Host-offload policy for ``cpu_checkpointing`` — saved residuals go to
    pinned host memory instead of HBM (the reference's explicit
    ``.cpu()`` copies, checkpointing.py:410-419)."""
    policies = jax.checkpoint_policies
    maker = getattr(policies, "offload_dot_with_no_batch_dims", None)
    if maker is None:
        logger.warning(
            "cpu_checkpointing requested but this jax version has no offload "
            "checkpoint policy; falling back to full rematerialization")
        return policies.nothing_saveable
    try:
        return maker("device", "pinned_host")
    except TypeError:
        return policies.nothing_saveable


_NAMED_POLICIES = {
    # Full remat: save only segment inputs — the reference's behaviour.
    "nothing": lambda: jax.checkpoint_policies.nothing_saveable,
    # Save every matmul output (skip recomputing MXU work, re-do the cheap
    # elementwise ops) — the standard TPU selective-remat policy.
    "dots": lambda: jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch": lambda: jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    "everything": lambda: jax.checkpoint_policies.everything_saveable,
    "offload": _offload_policy,
}


def make_policy(name=None):
    """Resolve a checkpoint policy by name or from the configured state."""
    if callable(name):
        return name
    if name is None:
        name = "offload" if _cfg().cpu_checkpointing else "nothing"
    try:
        return _NAMED_POLICIES[name]()
    except KeyError:
        raise ValueError(
            f"unknown checkpoint policy {name!r}; "
            f"one of {sorted(_NAMED_POLICIES)}")


# ---------------------------------------------------------------------------
# checkpoint()
# ---------------------------------------------------------------------------

def _partition_constraint(tree, axis="model"):
    """Shard checkpointed inputs over the model axis — the
    ``partition_activations`` capability (reference 369-397) as a GSPMD
    sharding constraint. Outside a mesh context this is a no-op."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.utils.compat import get_abstract_mesh
    mesh = get_abstract_mesh()
    if mesh is None or not mesh.shape or axis not in mesh.shape \
            or mesh.shape[axis] == 1:
        return tree

    def constrain(x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return x
        # Shard the trailing (feature/hidden) dim — what the reference's
        # flatten-and-split over MP ranks amounts to. Walk backwards so the
        # batch dim (dim 0, owned by the data axis) is only used as a last
        # resort for 1-D values.
        size = mesh.shape[axis]
        for d in range(x.ndim - 1, -1, -1):
            if x.shape[d] % size == 0 and x.shape[d] > 0:
                spec = [None] * x.ndim
                spec[d] = axis
                return jax.lax.with_sharding_constraint(x, P(*spec))
        return x

    return jax.tree_util.tree_map(constrain, tree)


@contextlib.contextmanager
def _profiled(name):
    if _cfg().profile and _timers is not None:
        _timers(name).start()
        try:
            yield
        finally:
            _timers(name).stop()
            _timers.log([name])
    else:
        yield


def checkpoint(function, *args, policy=None, static_argnums=(),
               prevent_cse=False):
    """Checkpoint a model segment: recompute its activations in backward
    instead of storing them (reference ``checkpoint``, checkpointing.py:579).

    Unlike the reference this composes with jit/scan/pjit and needs no RNG
    state capture — pass PRNG keys as explicit ``args`` and dropout is
    bitwise-identical in the rematerialized forward.
    """
    cfg = _cfg()
    ckpt_policy = make_policy(policy)

    fn = function
    if cfg.partition_activations:
        inner = function

        def fn(*inner_args):
            return inner(*_partition_constraint(inner_args))

    wrapped = jax.checkpoint(fn, policy=ckpt_policy,
                             prevent_cse=prevent_cse,
                             static_argnums=static_argnums)
    with _profiled("activation_checkpoint"):
        out = wrapped(*args)
    if cfg.synchronize_checkpoint_boundary:
        # The reference cuda-synchronizes at segment boundaries (331-335);
        # under jit this is a trace-time no-op, but eagerly it makes the
        # profile timers honest.
        jax.tree_util.tree_map(
            lambda x: x.block_until_ready()
            if hasattr(x, "block_until_ready") else x, out)
    return out


def checkpoint_sequential(functions: Sequence[Callable], x,
                          num_checkpoints=None, policy=None):
    """Apply ``functions`` in order, checkpointing in ``num_checkpoints``
    equal segments (the reference's Megatron usage pattern: checkpoint every
    ``checkpoint-num-layers`` block; segment count from config
    ``number_checkpoints``)."""
    n = len(functions)
    segs = num_checkpoints or _cfg().number_checkpoints or n
    segs = max(1, min(segs, n))
    bounds = [round(i * n / segs) for i in range(segs + 1)]
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        if lo == hi:
            continue

        def segment(y, fns=tuple(functions[lo:hi])):
            for f in fns:
                y = f(y)
            return y

        x = checkpoint(segment, x, policy=policy)
    return x


# ---------------------------------------------------------------------------
# RNG tracking — deterministic named key derivation.
# ---------------------------------------------------------------------------

class RNGKeyTracker:
    """Named PRNG key tracker (the ``CudaRNGStatesTracker`` capability,
    reference checkpointing.py:147-220, without any state capture: JAX keys
    are values, so "restoring the RNG state in backward" is just reusing the
    same key).

    ``add(name, seed)`` registers a stream; ``fork(name)`` yields a fresh
    per-use subkey, advancing the stream deterministically.
    """

    def __init__(self):
        self._keys = {}
        self._counts = {}

    def reset(self):
        self._keys.clear()
        self._counts.clear()

    def get_states(self):
        return dict(self._keys), dict(self._counts)

    def set_states(self, states):
        keys, counts = states
        self._keys = dict(keys)
        self._counts = dict(counts)

    def add(self, name, seed):
        if name in self._keys:
            raise Exception(f"RNG stream {name} already present")
        self._keys[name] = jax.random.PRNGKey(seed)
        self._counts[name] = 0

    @contextlib.contextmanager
    def fork(self, name="model-parallel-rng"):
        """Yield a fresh subkey for the named stream (reference ``fork``,
        checkpointing.py:192-220 swaps global CUDA RNG state; here the
        subkey is handed to the caller explicitly)."""
        if name not in self._keys:
            raise Exception(f"RNG stream {name} not added")
        sub = jax.random.fold_in(self._keys[name], self._counts[name])
        self._counts[name] += 1
        yield sub


_RNG_TRACKER = RNGKeyTracker()
_MODEL_PARALLEL_RNG = "model-parallel-rng"


def get_rng_tracker():
    """Reference ``get_cuda_rng_tracker`` (checkpointing.py:265)."""
    return _RNG_TRACKER


def model_parallel_seed(seed, model_parallel_rank=0, offset=2718):
    """Seed two streams the way Megatron does (reference
    ``model_parallel_cuda_manual_seed``, checkpointing.py:223-262): a
    ``default`` stream identical on all MP ranks (data-parallel dropout)
    and a ``model-parallel-rng`` stream offset per MP rank (different
    dropout on each tensor-parallel shard of an activation)."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("default", seed)
    _RNG_TRACKER.add(_MODEL_PARALLEL_RNG,
                     seed + offset + model_parallel_rank)
    return _RNG_TRACKER
