"""Elastic batch solver: preserve the effective batch across world sizes.

DeepSpeed's batch triple is ``train_batch = micro * grad_accum * world``
(`runtime/config.py`). When the world size changes on resume, a pinned
micro/accum pair generally no longer factors the same global batch —
silently training at a different effective batch would shift the loss
curve and desynchronize the LR schedule (which advances per optimizer
step). :func:`solve_elastic_batch` re-derives ``micro x accum`` for the
new world so the global batch (and therefore the optimizer-step count
per epoch, i.e. the LR schedule) is preserved exactly whenever the
target divides; when it cannot divide, it picks the nearest achievable
global batch and reports the LR scale the configured rule prescribes
(linear/sqrt, per the large-batch scaling literature) — or raises under
``strict``.
"""

import math
from typing import NamedTuple, Optional

from deepspeed_tpu.runtime.elastic.errors import ElasticResumeError

LR_SCALING_LINEAR = "linear"
LR_SCALING_SQRT = "sqrt"
LR_SCALING_NONE = "none"
LR_SCALING_RULES = (LR_SCALING_LINEAR, LR_SCALING_SQRT, LR_SCALING_NONE)


class BatchPlan(NamedTuple):
    """One solved batch configuration for a given world size."""
    micro_batch: int       # train_micro_batch_size_per_gpu
    grad_accum: int        # gradient_accumulation_steps
    global_batch: int      # micro * accum * world (the achieved batch)
    world_size: int
    exact: bool            # achieved == target
    lr_scale: float        # 1.0 when exact; else per the scaling rule


def solve_elastic_batch(target_global_batch,
                        world_size,
                        prefer_micro: Optional[int] = None,
                        prefer_accum: Optional[int] = None,
                        max_micro: Optional[int] = None,
                        lr_scaling: str = LR_SCALING_LINEAR,
                        strict: bool = False) -> BatchPlan:
    """Factor ``target_global_batch`` as micro x accum x world_size.

    Preference order for the per-rank factorization: keep the user's
    micro batch if it still divides, else keep their accum steps, else
    minimize accum (``accum=1``, bounded by ``max_micro`` when given).
    ``strict`` turns an inexact target into :class:`ElasticResumeError`
    instead of an LR-scaled approximation.
    """
    target = int(target_global_batch)
    world = int(world_size)
    if target <= 0:
        raise ValueError(f"target_global_batch must be > 0, got {target}")
    if world <= 0:
        raise ValueError(f"world_size must be > 0, got {world}")
    if lr_scaling not in LR_SCALING_RULES:
        raise ValueError(f"lr_scaling must be one of {LR_SCALING_RULES}, "
                         f"got {lr_scaling!r}")

    q, r = divmod(target, world)
    if r == 0:
        achieved, per_rank, exact = target, q, True
    else:
        if strict:
            raise ElasticResumeError(
                f"elasticity.strict: target_global_batch {target} does "
                f"not divide by world size {world} — no micro x accum "
                "factoring preserves the effective batch exactly")
        # Nearest achievable multiple of the world size (at least one
        # sample per rank), integer round-half-up.
        per_rank = max(1, q + (1 if 2 * r >= world else 0))
        achieved, exact = per_rank * world, False

    if prefer_micro and per_rank % int(prefer_micro) == 0:
        micro = int(prefer_micro)
    elif prefer_accum and per_rank % int(prefer_accum) == 0:
        micro = per_rank // int(prefer_accum)
    else:
        micro = per_rank
    if max_micro and micro > int(max_micro):
        # Smallest accum that brings the micro batch under the cap while
        # still dividing per_rank evenly.
        micro = next((per_rank // a for a in range(1, per_rank + 1)
                      if per_rank % a == 0 and
                      per_rank // a <= int(max_micro)), 1)
    accum = per_rank // micro

    if exact or lr_scaling == LR_SCALING_NONE:
        lr_scale = 1.0
    elif lr_scaling == LR_SCALING_SQRT:
        lr_scale = math.sqrt(achieved / target)
    else:
        lr_scale = achieved / target

    return BatchPlan(micro_batch=micro, grad_accum=accum,
                     global_batch=achieved, world_size=world,
                     exact=exact, lr_scale=lr_scale)
