"""Elasticity: topology-agnostic checkpoints and reshard-on-resume.

A checkpoint saved at data-parallel world size N is a deterministic
relayout away from world size M — ZeRO here is GSPMD sharding
declarations over the ``data`` axis (`runtime/zero/sharding.py`), so the
logical arrays never depend on the world size. This package owns that
relayout plus the batch/LR bookkeeping DeepSpeed's elasticity config
standardized:

- :mod:`topology` — topology capture/compare + the manifest's
  PartitionSpec (de)serialization; typed
  :class:`CheckpointTopologyError` / :class:`ElasticResumeError`.
- :mod:`batch` — :func:`solve_elastic_batch`: re-derive
  micro x grad_accum for a new world so the effective batch (and LR
  schedule) is preserved, or scale LR by the configured rule.
- :mod:`reshard` — streaming host->device placement for resume and the
  offline checkpoint rewriter behind ``bin/ds_tpu_reshard``.

Engine wiring rides the ``elasticity`` config block
(`runtime/config.py`); see docs/elasticity.md.
"""

from deepspeed_tpu.runtime.elastic.errors import (
    CheckpointTopologyError,
    ElasticResumeError,
)
from deepspeed_tpu.runtime.elastic.batch import (
    BatchPlan,
    solve_elastic_batch,
)
from deepspeed_tpu.runtime.elastic.topology import (
    TopologyCheck,
    check_topology,
    current_topology,
)
from deepspeed_tpu.runtime.elastic.reshard import (
    reshard_checkpoint,
    stream_device_put,
)

__all__ = [
    "BatchPlan",
    "CheckpointTopologyError",
    "ElasticResumeError",
    "TopologyCheck",
    "check_topology",
    "current_topology",
    "reshard_checkpoint",
    "solve_elastic_batch",
    "stream_device_put",
]
