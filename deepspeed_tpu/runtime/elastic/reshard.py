"""Resharder: reassemble logical arrays and re-partition to a new mesh.

Two consumers:

- **Engine resume** (`engine.load_checkpoint`): checkpoints restore as
  full host-numpy logical arrays (`resilience/checkpoint.py` sidesteps
  orbax's different-topology path on purpose); :func:`stream_device_put`
  places them leaf-by-leaf on the *current* mesh's shardings, dropping
  each host buffer as soon as its device copy exists so peak host
  memory is bounded by one extra leaf, not a second full state tree.
- **Offline CLI** (`bin/ds_tpu_reshard`): :func:`reshard_checkpoint`
  rewrites a checkpoint saved for world size N into one addressed to
  world size M without booting an engine — CRC-verified read, manifest
  ``topology``/``arrays`` sections retargeted (the elastic axis kept on
  the dims it occupied, dropped only where the new world size stops
  dividing them), atomic tmp+rename write, and a garbage-collected tmp on
  mid-write failure (the source checkpoint is never touched).
"""

import logging
import os
import shutil
import time

import numpy as np
import jax
from jax.sharding import PartitionSpec

from deepspeed_tpu.parallel.mesh import MESH_AXES
from deepspeed_tpu.runtime.elastic.topology import (
    spec_from_json,
    spec_to_json,
)
from deepspeed_tpu.runtime.resilience.checkpoint import (
    CheckpointCorruptError,
    CheckpointIOError,
    CheckpointManager,
)

logger = logging.getLogger(__name__)


def stream_device_put(tree, shardings):
    """Place a host pytree on device leaf-by-leaf, releasing each host
    buffer once its device copy is live.

    ``shardings`` is either a single Sharding (applied to every leaf) or
    a pytree congruent with ``tree``. A whole-tree ``jax.device_put``
    would keep every host leaf referenced until the full transfer is
    built; here the host array drops out of the flattened list as soon
    as its device leaf exists, so the only lingering host references are
    the ones the *caller* still holds.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    single = isinstance(shardings, (jax.sharding.Sharding,
                                    getattr(jax, "Device", ())))
    shard_leaves = [shardings] * len(leaves) if single \
        else treedef.flatten_up_to(shardings)
    out = []
    for i, sh in enumerate(shard_leaves):
        out.append(jax.device_put(leaves[i], sh))
        leaves[i] = None
    return jax.tree_util.tree_unflatten(treedef, out)


def _retarget_arrays(arrays, target_world, axis="data"):
    """Retarget each array's PartitionSpec for a new data-axis size.

    The elastic axis stays on exactly the dims it occupied in the saved
    spec, dropped only where the new world size no longer divides that
    dim. Keeping the placement (rather than re-solving it) makes the
    rewrite invertible — N→M→N reproduces the source manifest whenever
    divisibility holds both ways, including through M=1 where a re-solve
    would collapse the axis marker and lose it.
    """
    out = {}
    for key, rec in (arrays or {}).items():
        saved_spec = spec_from_json(rec.get("spec"))
        shape = tuple(int(d) for d in rec.get("shape") or ())
        entries = []
        for dim, entry in enumerate(tuple(saved_spec)):
            names = entry if isinstance(entry, tuple) else (entry,)
            if axis in names:
                if dim < len(shape) and shape[dim] % target_world == 0:
                    entries.append(entry)
                    continue
                logger.warning(
                    "leaf %s dim %d (size %s) not divisible by target "
                    "world %d: dropping %r from its spec (replicated)",
                    key, dim, shape[dim] if dim < len(shape) else "?",
                    target_world, axis)
                kept = tuple(n for n in names if n != axis)
                entries.append(kept if len(kept) > 1 else
                               (kept[0] if kept else None))
            else:
                entries.append(entry)
        out[key] = {**rec, "spec": spec_to_json(PartitionSpec(*entries))}
    return out


def reshard_checkpoint(src_dir, dst_dir, target_world, tag=None,
                       io_retries=3, io_retry_base_s=0.05):
    """Rewrite the checkpoint at ``src_dir`` for ``target_world`` data-
    parallel ranks into ``dst_dir``; returns a summary dict.

    The source is CRC-verified on read and never modified. The target is
    written through the same atomic tmp+rename path as engine saves; on
    I/O failure past the retry budget the partial tmp dir is removed and
    :class:`CheckpointIOError` propagates — ``dst_dir`` never holds a
    partial final checkpoint. Array bytes pass through untouched (the
    logical arrays are world-size-independent); what changes is the
    manifest's topology/arrays addressing and the meta's world size.
    """
    target_world = int(target_world)
    if target_world < 1:
        raise ValueError(f"target world size must be >= 1, "
                         f"got {target_world}")
    t0 = time.perf_counter()
    src_mgr = CheckpointManager(save_dir=src_dir, io_retries=io_retries,
                                io_retry_base_s=io_retry_base_s,
                                process_index=0, process_count=1)
    resolved = src_mgr.resolve_tag(src_dir, tag)
    if resolved is None:
        raise CheckpointCorruptError(
            src_dir, "no valid checkpoint to reshard")
    src_path = src_mgr.ckpt_path(src_dir, resolved)
    manifest = src_mgr.validate(src_path)
    state, meta, _ = src_mgr.load(src_dir, resolved)

    src_topo = dict(manifest.get("topology") or {})
    src_mesh = dict(src_topo.get("mesh_shape") or
                    {a: 1 for a in MESH_AXES})
    src_world = int(src_mesh.get("data") or
                    meta.get("dp_world_size") or 1)
    hard = {a: int(src_mesh.get(a) or 1) for a in ("model", "seq", "expert")
            if int(src_mesh.get(a) or 1) > 1}
    if hard:
        logger.warning(
            "resharding a checkpoint with non-trivial %s axes: only the "
            "data axis is retargeted", hard)

    new_mesh = dict(src_mesh)
    new_mesh["data"] = target_world
    new_topo = dict(src_topo)
    new_topo.update({"mesh_shape": new_mesh, "process_count": 1})
    arrays = manifest.get("arrays")
    if arrays:
        arrays = _retarget_arrays(arrays, target_world)

    new_meta = dict(meta)
    new_meta["dp_world_size"] = target_world
    new_meta["resharded_from"] = {"dp_world_size": src_world,
                                  "path": src_path}

    dst_mgr = CheckpointManager(save_dir=dst_dir, io_retries=io_retries,
                                io_retry_base_s=io_retry_base_s,
                                process_index=0, process_count=1)
    extra = {"topology": new_topo}
    if arrays is not None:
        extra["arrays"] = arrays
    try:
        dst_path = dst_mgr.save(dst_dir, resolved, state, new_meta,
                                extra_manifest=extra, fault_op="reshard")
    except CheckpointIOError:
        # The atomic-save contract leaves at most a tmp dir behind; GC it
        # so the target directory holds no partial bytes at all.
        shutil.rmtree(dst_mgr._tmp_path(dst_dir, resolved),
                      ignore_errors=True)
        raise
    dst_mgr.validate(dst_path)

    n_bytes = sum(int(np.asarray(leaf).nbytes)
                  for leaf in jax.tree_util.tree_leaves(state))
    summary = {
        "tag": resolved,
        "src_path": src_path,
        "dst_path": dst_path,
        "src_world": src_world,
        "target_world": target_world,
        "n_leaves": len(jax.tree_util.tree_leaves(state)),
        "state_bytes": n_bytes,
        "wall_s": round(time.perf_counter() - t0, 6),
    }
    # Offline resharding has no engine; log through the process-default
    # telemetry session when one exists (an engine in this process, or a
    # caller that installed one for the CLI).
    from deepspeed_tpu.telemetry import get_default_session
    session = get_default_session()
    if session is not None:
        session.emit("reshard", **summary)
    return summary
