"""Checkpoint topology capture + compatibility policy.

A checkpoint manifest (format v2, `resilience/checkpoint.py`) carries a
``topology`` section recording the mesh shape, process count, ZeRO stage
and offload flag at save time, plus an ``arrays`` section with each
leaf's logical shape, dtype and PartitionSpec. That makes any checkpoint
self-describing: :func:`check_topology` compares the saved topology with
the live engine's and classifies the load instead of letting a mismatch
surface as an opaque orbax/shape error.

Classification policy (the engine's actual capabilities, not wishes):

- ``same``      — identical topology; plain restore.
- ``unknown``   — pre-elastic checkpoint (no topology recorded); the
  engine loads it as before, shape errors surface at placement time.
- ``restage``   — the ``pipe`` axis changed. Pipeline restage-on-load
  (`engine._reshape_for_restage`) predates elasticity and validates
  payload dims itself, so this stays allowed with or without the
  ``elasticity`` block (the accompanying ``data``-axis recount over a
  fixed device pool is part of the same supported path).
- ``relayout``  — only the ZeRO stage changed. Sharding declarations are
  a pure relayout of the same logical arrays; always allowed.
- ``elastic``   — the ``data`` axis size or process count changed.
  Allowed only with ``elasticity.enabled`` (the batch/LR bookkeeping
  must be re-solved); otherwise :class:`CheckpointTopologyError`.
- hard mismatch — ``model``/``seq``/``expert`` axis or the offload flag
  changed: :class:`ElasticResumeError` regardless of config. Tensor/
  sequence/expert parallel degrees change what the saved arrays *mean*
  (or, for offload, the state-tree structure), not just their layout.
"""

from typing import NamedTuple

import jax
from jax.sharding import PartitionSpec

from deepspeed_tpu.parallel.mesh import MESH_AXES, mesh_shape_dict
from deepspeed_tpu.runtime.elastic.errors import (
    CheckpointTopologyError,
    ElasticResumeError,
)

# Axes whose size elasticity can absorb (pure relayout over the mesh)
# vs. axes that change the meaning/partitioning of the model itself.
ELASTIC_AXES = ("data",)
RESTAGE_AXES = ("pipe",)
HARD_AXES = ("model", "seq", "expert")


def current_topology(mesh, zero_stage=0, offload=False, process_count=None,
                     param_layout=None):
    """The live engine's topology, in the manifest's schema.

    ``param_layout`` records how transformer layers are laid out in the
    param pytree (``"stacked"`` for `scan_layers` models — one ``"h"``
    entry with a leading layer axis — ``"per_layer"`` for unrolled
    ``h_<i>`` entries); None omits the field, keeping pre-scan
    manifests byte-identical.
    """
    if process_count is None:
        process_count = jax.process_count()
    topo = {
        "mesh_shape": mesh_shape_dict(mesh),
        "process_count": int(process_count),
        "zero_stage": int(zero_stage),
        "offload": bool(offload),
    }
    if param_layout is not None:
        topo["param_layout"] = str(param_layout)
    return topo


def param_layout(params):
    """Detect the layer layout of a param pytree's top level: "stacked"
    (a ``"h"`` key — `scan_layers`), "per_layer" (``h_<i>`` keys), or
    None for models without named transformer layers. Pure key
    inspection, so the engine can record it without importing model
    code."""
    try:
        keys = {str(k) for k in params}
    except TypeError:
        return None
    if "h" in keys:
        return "stacked"
    if any(k.startswith("h_") and k[2:].isdigit() for k in keys):
        return "per_layer"
    return None


class TopologyCheck(NamedTuple):
    kind: str      # same | unknown | restage | relayout | elastic
    changed: dict  # field -> (saved, current), empty for same/unknown


def _axis_sizes(topo):
    shape = dict(topo.get("mesh_shape") or {})
    return {a: int(shape.get(a, 1) or 1) for a in MESH_AXES}


def check_topology(saved, current, elastic=False):
    """Classify a checkpoint/engine topology pair; raise typed errors.

    ``saved`` is the manifest's topology section (None for pre-elastic
    checkpoints), ``current`` the live engine's (:func:`current_topology`).
    Returns a :class:`TopologyCheck`; raises
    :class:`ElasticResumeError` for hard mismatches and
    :class:`CheckpointTopologyError` for elastic-only mismatches when
    ``elastic`` is False.
    """
    if not saved:
        return TopologyCheck("unknown", {})

    changed = {}
    s_axes, c_axes = _axis_sizes(saved), _axis_sizes(current)
    for axis in MESH_AXES:
        if s_axes[axis] != c_axes[axis]:
            changed[axis] = (s_axes[axis], c_axes[axis])
    for field in ("process_count", "zero_stage", "offload",
                  "param_layout"):
        s, c = saved.get(field), current.get(field)
        if s is not None and c is not None and s != c:
            changed[field] = (s, c)

    if not changed:
        return TopologyCheck("same", {})

    if "param_layout" in changed:
        s, c = changed["param_layout"]
        raise ElasticResumeError(
            f"checkpoint stores {s} layer params but the model expects "
            f"{c}: the pytree structures differ, not just the "
            "placement. Convert the checkpoint first "
            "(models.gpt2.stack_gpt2_layer_params / "
            "unstack_gpt2_layer_params) or build the model with the "
            "matching scan_layers setting.", saved=saved, current=current)

    hard = [a for a in HARD_AXES if a in changed]
    if hard or "offload" in changed:
        what = (f"offload={changed['offload'][0]} -> "
                f"{changed['offload'][1]}" if "offload" in changed else
                ", ".join(f"{a}={changed[a][0]} -> {changed[a][1]}"
                          for a in hard))
        raise ElasticResumeError(
            f"checkpoint cannot be resumed on this topology: {what} "
            "changed. Resharding covers data-parallel world size and "
            "ZeRO layout only — a tensor/sequence/expert-parallel degree "
            "or offload change alters what the saved arrays mean, not "
            "just their placement.", saved=saved, current=current)

    if any(a in changed for a in RESTAGE_AXES):
        # Pipeline restage-on-load owns this case (including the data-axis
        # recount over the same device pool); payload-dim validation
        # happens leaf-wise in the engine.
        return TopologyCheck("restage", changed)

    needs_elastic = [k for k in changed if k in ELASTIC_AXES or
                     k == "process_count"]
    if needs_elastic:
        if not elastic:
            desc = ", ".join(f"{k}: {changed[k][0]} -> {changed[k][1]}"
                             for k in needs_elastic)
            raise CheckpointTopologyError(
                f"checkpoint was saved under a different topology "
                f"({desc}) and elasticity is disabled. Set "
                '{"elasticity": {"enabled": true}} to reshard-on-resume '
                "(or use bin/ds_tpu_reshard to rewrite the checkpoint "
                "offline).", saved=saved, current=current)
        return TopologyCheck("elastic", changed)

    # Only zero_stage differs: sharding declarations are a relayout of
    # the same logical arrays — always loadable.
    return TopologyCheck("relayout", changed)


# ----------------------------------------------------------------------
# PartitionSpec (de)serialization for the manifest's arrays section
# ----------------------------------------------------------------------

def spec_to_json(spec):
    """PartitionSpec -> JSON list (str | None | [str, ...] per dim)."""
    if spec is None:
        return None
    out = []
    for entry in tuple(spec):
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append(list(entry))
    return out


def spec_from_json(data):
    """Inverse of :func:`spec_to_json` (None -> replicated)."""
    if data is None:
        return PartitionSpec()
    return PartitionSpec(
        *[tuple(e) if isinstance(e, list) else e for e in data])


def strip_axis(spec, axis="data"):
    """Remove every occurrence of ``axis`` from a PartitionSpec.

    Recovers the base (pre-ZeRO) spec from a saved one so the resharder
    can re-run the zero partitioning decision for a new axis size.
    """
    entries = []
    for e in tuple(spec or ()):
        if e == axis:
            entries.append(None)
        elif isinstance(e, (tuple, list)):
            kept = tuple(a for a in e if a != axis)
            entries.append(kept if len(kept) > 1
                           else (kept[0] if kept else None))
        else:
            entries.append(e)
    return PartitionSpec(*entries)
