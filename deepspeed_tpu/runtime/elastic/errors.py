"""Typed errors for topology-aware checkpoint loads.

Before this subsystem, loading a checkpoint saved under a different
topology either worked by accident (single-process dp resize), went
through the pipeline restage path, or died deep inside orbax/XLA with an
opaque shape error. These exceptions make the outcome explicit: a
checkpoint/engine topology mismatch is always reported as a
:class:`CheckpointTopologyError`, and the subset of mismatches that no
amount of resharding can bridge (tensor-parallel degree changed, offload
flipped) as the :class:`ElasticResumeError` refinement.
"""


class CheckpointTopologyError(RuntimeError):
    """The checkpoint was saved under a different topology than the
    engine is running on, and ``elasticity.enabled`` is off.

    Carries ``saved`` / ``current`` topology dicts (as recorded in the
    checkpoint manifest / observed on the live mesh) so callers can log
    or decide without re-parsing the message.
    """

    def __init__(self, message, saved=None, current=None):
        super().__init__(message)
        self.saved = saved
        self.current = current


class ElasticResumeError(CheckpointTopologyError):
    """The topology change is one elasticity cannot bridge — e.g. the
    tensor-parallel (``model``) degree changed, or ZeRO-Offload was
    toggled (the optimizer-state tree has a different structure). Raised
    whether or not elasticity is enabled."""
