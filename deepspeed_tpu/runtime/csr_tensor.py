"""Row-sparse (CSR-style) tensors for sparse embedding gradients.

Capability parity with the reference's ``CSRTensor``
(`runtime/csr_tensor.py:11`) and its engine-side sparse allreduce
(`runtime/engine.py:177-183,1157-1213`): embedding-layer gradients are
communicated as (row-indices, row-values) pairs so comm volume scales with
the number of *touched* rows, not the vocabulary size.

TPU-native differences:
- shapes are static under jit: a CSRTensor carries a fixed row-capacity
  ``k`` (the reference pads ranks to the max nnz before allgather,
  engine.py:1187-1198 — same idea, decided at trace time);
- the collective is an ``all_gather`` of indices+values over the ``data``
  mesh axis inside ``shard_map`` (the reference's sparse_allreduce_bucket);
- duplicate row indices are legal and resolved by scatter-add in
  :meth:`CSRTensor.to_dense` (segment-sum semantics, like the reference's
  sum over repeated indices);
- :func:`embedding_grad_csr` builds the CSR gradient directly from the
  (token-ids, output-grad) pair — the dense [vocab, d] gradient never
  materializes, which the torch version gets from ``nn.Embedding
  (sparse=True)``.
"""

import dataclasses

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.compat import axis_size

__all__ = ["CSRTensor", "csr_allreduce", "embedding_grad_csr",
           "dense_to_csr"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class CSRTensor:
    """Row-sparse tensor: ``dense[indices[i]] += values[i]``.

    ``indices`` [k] int32 row ids (duplicates allowed), ``values`` [k, d]
    rows, ``dense_rows`` static total row count. Registered as a pytree
    (``dense_rows`` static) so it flows through jit/shard_map.
    """
    indices: jnp.ndarray
    values: jnp.ndarray
    dense_rows: int = dataclasses.field(metadata=dict(static=True))

    @property
    def row_dim(self):
        return self.values.shape[-1]

    def to_dense(self):
        """Scatter-add into the dense [dense_rows, d] array (duplicate
        indices accumulate — the reference's repeated-index sum)."""
        out = jnp.zeros((self.dense_rows, self.values.shape[-1]),
                        self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self):
        return self.values.size + self.indices.size

    def add(self, other: "CSRTensor") -> "CSRTensor":
        assert self.dense_rows == other.dense_rows
        return CSRTensor(
            indices=jnp.concatenate([self.indices, other.indices]),
            values=jnp.concatenate([self.values, other.values]),
            dense_rows=self.dense_rows)


def dense_to_csr(dense, k=None):
    """Sparsify a dense [rows, d] gradient to its top-``k`` rows by L1 mass
    (jit-safe static shape; ``k`` defaults to all rows). Rows beyond the
    true support come out as zero-value rows — harmless under scatter-add."""
    rows = dense.shape[0]
    k = rows if k is None else min(k, rows)
    mass = jnp.abs(dense).sum(axis=tuple(range(1, dense.ndim)))
    _, idx = jax.lax.top_k(mass, k)
    idx = idx.astype(jnp.int32)
    return CSRTensor(indices=idx, values=dense[idx], dense_rows=rows)


def embedding_grad_csr(ids, dout, vocab_size):
    """The gradient of ``table[ids]`` w.r.t. ``table`` in CSR form.

    ``ids`` [...]; ``dout`` [..., d] cotangent of the lookup output. The
    result has ``k = ids.size`` rows — the dense [vocab, d] array is never
    built (the point of the reference's sparse-embedding path).
    """
    d = dout.shape[-1]
    return CSRTensor(indices=ids.reshape(-1).astype(jnp.int32),
                     values=dout.reshape(-1, d),
                     dense_rows=vocab_size)


def csr_allreduce(csr: CSRTensor, axis_name="data", average=True):
    """Sum (or average) a CSRTensor across the mesh axis; call inside
    ``shard_map``. Comm volume per device is ``world * k * (d+1)`` words vs
    ``2 * vocab * d`` for a dense allreduce — the win whenever
    ``world * k << vocab`` (reference engine.py:1157-1213).

    Returns a CSRTensor with the concatenated (still-duplicated) rows,
    exactly like the reference's allgathered result; ``to_dense`` resolves
    duplicates.
    """
    world = axis_size(axis_name)
    all_idx = jax.lax.all_gather(csr.indices, axis_name)    # [world, k]
    all_val = jax.lax.all_gather(csr.values, axis_name)     # [world, k, d]
    values = all_val.reshape(world * csr.indices.shape[0], -1)
    if average:
        values = values / world
    return CSRTensor(indices=all_idx.reshape(-1),
                     values=values,
                     dense_rows=csr.dense_rows)
