"""Chunk codec registry shared by every quantized-communication path.

One codec = one wire dtype (``int8``, ``f8e4m3fn``, ``f8e5m2``) plus the
per-chunk absmax scaling recipe PR 1 introduced for the bracketed int8
all-reduce. The bracketed all-reduce (:mod:`.quantized`), the overlapped
``ppermute`` rings (:mod:`deepspeed_tpu.parallel.collectives`) and the
stage-3 gather path all encode and decode through these functions, so the
numerics are defined in exactly one place.

``encode_chunks``/``decode_chunks`` generalize the legacy
``quantize_chunks``/``dequantize_chunks`` pair: for the ``int8`` codec
they are bit-for-bit the PR 1 semantics (scale = absmax/127, zero-chunk
guard, round + clip, decode as ``q * scale``); the fp8 codecs swap the
integer round for a saturating cast into the target float format.

The ``*_wire`` helpers byte-pack payload and f32 scales into ONE 1-D u8
buffer (``lax.ppermute`` moves arrays, not pytrees) so a ring hop moves
chunk data and its scales in a single collective operand.
"""

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class Codec:
    """A wire format: target dtype + largest representable magnitude."""

    name: str
    dtype: object
    qmax: float
    integer: bool = False

    @property
    def itemsize(self):
        return jnp.dtype(self.dtype).itemsize


CODECS = {
    "int8": Codec("int8", jnp.int8, 127.0, integer=True),
    "f8e4m3fn": Codec("f8e4m3fn", jnp.float8_e4m3fn, 448.0),
    "f8e5m2": Codec("f8e5m2", jnp.float8_e5m2, 57344.0),
}


def get_codec(codec):
    """Resolve a codec name (or pass through a Codec / None)."""
    if codec is None or isinstance(codec, Codec):
        return codec
    try:
        return CODECS[codec]
    except KeyError:
        raise ValueError(
            f"unknown wire codec {codec!r}; expected one of "
            f"{sorted(CODECS)}")


def encode_chunks(x, chunk_size, codec="int8"):
    """Flatten ``x`` into ``chunk_size`` chunks and quantize each with a
    per-chunk absmax scale. Returns ``(q, scales)`` where ``q`` has shape
    ``[n_chunks, chunk_size]`` in the codec dtype and ``scales`` is f32
    ``[n_chunks]``. ``x.size`` must be a multiple of ``chunk_size``.
    """
    codec = get_codec(codec)
    chunks = x.reshape(-1, chunk_size).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(chunks), axis=1)
    scale = absmax / codec.qmax
    safe = jnp.where(scale > 0.0, scale, 1.0)
    scaled = chunks / safe[:, None]
    if codec.integer:
        q = jnp.clip(jnp.round(scaled), -codec.qmax, codec.qmax)
    else:
        q = jnp.clip(scaled, -codec.qmax, codec.qmax)
    return q.astype(codec.dtype), scale


def decode_chunks(q, scales, dtype=jnp.float32):
    """Inverse of :func:`encode_chunks`: returns a flat array of
    ``q.size`` values in ``dtype`` (legacy PR 1 semantics: the product is
    taken directly in ``dtype``)."""
    vals = q.astype(dtype) * scales[:, None].astype(dtype)
    return vals.reshape(-1)


# ----------------------------------------------------------------------
# single-buffer wire packing: payload + scales in one 1-D u8 array
# ----------------------------------------------------------------------

def _wire_chunk_size(n, chunk_size):
    """Effective chunk length for an ``n``-element payload."""
    return max(1, min(int(chunk_size), int(n)))


def wire_layout(shape, codec, chunk_size=512):
    """Static layout of the packed wire buffer for a payload of ``shape``:
    ``(n, c, n_chunks, payload_bytes, total_bytes)``."""
    codec = get_codec(codec)
    n = int(math.prod(shape)) if shape else 1
    c = _wire_chunk_size(n, chunk_size)
    n_chunks = -(-n // c)
    payload_bytes = n_chunks * c * codec.itemsize
    return n, c, n_chunks, payload_bytes, payload_bytes + 4 * n_chunks


def wire_nbytes(shape, codec, chunk_size=512):
    """Bytes on the wire for one encoded payload of ``shape``."""
    return wire_layout(shape, codec, chunk_size)[-1]


def encode_wire(x, codec, chunk_size=512):
    """Quantize ``x`` and pack ``(q, scales)`` into one flat u8 buffer.

    The payload is zero-padded up to a chunk multiple, quantized with
    :func:`encode_chunks`, and both the codec-dtype payload and the f32
    scales are bitcast to u8 and concatenated — so the whole thing rides
    a single ``ppermute``/``all_gather`` operand. Layout:
    ``[payload_bytes | 4 * n_chunks scale bytes]``.
    """
    codec = get_codec(codec)
    n, c, n_chunks, _, _ = wire_layout(x.shape, codec, chunk_size)
    flat = x.reshape(-1).astype(jnp.float32)
    pad = n_chunks * c - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    q, scales = encode_chunks(flat, c, codec)
    q_bytes = lax.bitcast_convert_type(q, jnp.uint8).reshape(-1)
    s_bytes = lax.bitcast_convert_type(scales, jnp.uint8).reshape(-1)
    return jnp.concatenate([q_bytes, s_bytes])


def decode_wire(wire, codec, shape, dtype=jnp.float32, chunk_size=512):
    """Inverse of :func:`encode_wire`: unpack + dequantize back to
    ``shape`` in ``dtype``."""
    codec = get_codec(codec)
    n, c, n_chunks, payload_bytes, total = wire_layout(
        shape, codec, chunk_size)
    q_bytes = wire[:payload_bytes]
    s_bytes = wire[payload_bytes:total]
    if codec.itemsize == 1:
        q = lax.bitcast_convert_type(
            q_bytes, codec.dtype).reshape(n_chunks, c)
    else:
        q = lax.bitcast_convert_type(
            q_bytes.reshape(-1, codec.itemsize),
            codec.dtype).reshape(n_chunks, c)
    scales = lax.bitcast_convert_type(
        s_bytes.reshape(n_chunks, 4), jnp.float32)
    flat = decode_chunks(q, scales, jnp.float32)[:n]
    return flat.reshape(shape).astype(dtype)
