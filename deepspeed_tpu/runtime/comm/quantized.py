"""Int8 chunk-scaled quantized all-reduce with backward-overlap bucketing.

The dense-DP / ZeRO-1/2 gradient sync ships fp32 on the wire; this module
replaces it with the EQuARX-style (arXiv:2506.17615) quantized exchange:

- the flat gradient is cut into fixed ``chunk_size`` pieces, each encoded
  as int8 against its own absmax scale (``scale = absmax / 127``);
- phase 1 is a reduce-scatter *in int8*: rank r receives every rank's
  quantized copy of shard r (the chunk-server ``all_to_all`` shared with
  the 1-bit path, `parallel/collectives.py:scatter_to_chunk_servers`);
- the server accumulates its shard in fp32 (one dequant + mean — the
  "local fp32 accumulate" that keeps the reduction exact no matter the
  world size), optionally re-applying a server error-feedback residual;
- phase 2 re-quantizes the reduced shard and all-gathers it in int8
  (`gather_from_chunk_servers`).

Wire cost per device on a ring of N (send-bytes basis, n fp32 elements,
chunk c): the int8 all_to_all moves (N-1)/N·(n + 4n/c) and the int8
all_gather the same again — about 1.75·n(1 + 4/c) bytes vs 7·n for the
fp32 ring all-reduce, a ~3.97x reduction at c = 512
(`tests/unit/test_quantized_comm_volume.py` pins this from compiled HLO).

Error feedback is optional: gradient averaging runs every step, so unlike
1-bit Adam the quantization noise is zero-mean and unbiased per chunk;
EF tightens the long-run bias at the cost of one n-sized residual per
rank plus one shard-sized server residual (carried by the caller as
explicit state, like `comm/compressed.py`).

The bucketing layer (:func:`bucket_plan` / :func:`quantized_allreduce_tree`)
groups the grad pytree into fixed-byte buckets, each synced by an
independent collective chain, so XLA's latency-hiding scheduler can
overlap the quantize+reduce of bucket k with the backward (or the
dequant/update) of bucket k+1 — the reference's allreduce bucketing
(engine.py:1082 ``allreduce_bucket``) expressed as graph structure.

All collective entry points must run inside ``shard_map`` with
``axis_name`` bound; quantize/dequantize are pure and testable anywhere.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.collectives import (
    gather_from_chunk_servers, scatter_to_chunk_servers)
from deepspeed_tpu.runtime.comm.codecs import decode_chunks, encode_chunks
from deepspeed_tpu.utils.compat import axis_size

__all__ = [
    "quantize_chunks", "dequantize_chunks", "quantized_allreduce",
    "quantized_allreduce_sizes", "bucket_plan", "init_residuals",
    "quantized_allreduce_tree",
]


def quantize_chunks(x, chunk_size):
    """Encode flat ``x`` (length divisible by ``chunk_size``) as
    ``(q, scales)``: int8 values against per-chunk absmax scales.

    ``q`` is ``[n_chunks, chunk_size]`` int8 in [-127, 127]; ``scales`` is
    ``[n_chunks]`` fp32 with ``scale = absmax / 127`` (all-zero chunks get
    scale 0, decoding back to exact zeros).

    Thin wrapper over the ``int8`` entry of the codec registry
    (:mod:`.codecs`) — the registry is the single source of truth for the
    chunk numerics shared with the overlapped rings and stage-3 gathers."""
    return encode_chunks(x, chunk_size, "int8")


def dequantize_chunks(q, scales, dtype=jnp.float32):
    """Inverse of :func:`quantize_chunks` (up to rounding): flat array."""
    return decode_chunks(q, scales, dtype)


def quantized_allreduce_sizes(n, world, chunk_size):
    """(padded_n, shard) for an n-element buffer: ``padded_n`` is the
    smallest multiple of ``world * chunk_size`` >= n, so every rank serves
    a whole number of chunks (padding decodes to exact zeros)."""
    align = world * chunk_size
    padded = ((n + align - 1) // align) * align
    return padded, padded // world


def quantized_allreduce(x, axis_name, chunk_size=512,
                        worker_residual=None, server_residual=None):
    """Int8 chunk-scaled *averaging* all-reduce of flat ``x`` over
    ``axis_name``. Must run inside ``shard_map``; ``x.shape[-1]`` must be
    a multiple of ``world * chunk_size`` (:func:`quantized_allreduce_sizes`).

    ``worker_residual`` ([n], per rank) and ``server_residual``
    ([n/world], for the shard this rank serves) enable error feedback when
    both are given: the residuals are added before each quantization and
    the new quantization errors returned for the caller to carry.

    Returns ``(avg, new_worker_residual, new_server_residual)`` — the
    residuals are ``None`` when error feedback is off."""
    world = axis_size(axis_name)
    n = x.shape[-1]
    shard = n // world
    assert shard * world == n and shard % chunk_size == 0, (
        f"buffer of {n} not aligned for world {world} x chunk "
        f"{chunk_size}; use quantized_allreduce_sizes()")
    ef = worker_residual is not None

    # Worker quantization (+ optional error feedback).
    corrected = x + worker_residual if ef else x
    q, scales = quantize_chunks(corrected, chunk_size)
    new_worker = corrected - dequantize_chunks(q, scales) if ef else None

    # Reduce-scatter in int8: rank r collects every rank's shard r.
    cps = shard // chunk_size  # chunks per shard
    recv_q, recv_s = scatter_to_chunk_servers(
        (q.reshape(world, cps, chunk_size), scales.reshape(world, cps)),
        axis_name)

    # Local fp32 accumulate of the served shard.
    shard_avg = (recv_q.astype(jnp.float32) *
                 recv_s[:, :, None]).mean(axis=0).reshape(shard)
    if ef:
        shard_avg = shard_avg + server_residual

    # Re-quantize + all-gather in int8.
    q2, s2 = quantize_chunks(shard_avg, chunk_size)
    new_server = shard_avg - dequantize_chunks(q2, s2) if ef else None
    all_q, all_s = gather_from_chunk_servers((q2, s2), axis_name)
    avg = dequantize_chunks(all_q.reshape(-1, chunk_size),
                            all_s.reshape(-1))
    return avg, new_worker, new_server


def bucket_plan(leaves, world, bucket_bytes, chunk_size):
    """Group flat leaf sizes into fixed-byte buckets.

    ``leaves`` is a list of (flattened) element counts in pytree order.
    Greedy in-order packing: a bucket closes once it holds >=
    ``bucket_bytes`` worth of fp32 elements, so consecutive backward-order
    leaves share a collective while the pytree order (and therefore the
    caller's concat/split bookkeeping) stays trivial.

    Returns a list of buckets, each ``(leaf_slice, n, padded_n)`` where
    ``leaf_slice`` indexes the member leaves, ``n`` their total elements,
    and ``padded_n`` the aligned buffer size from
    :func:`quantized_allreduce_sizes`."""
    per_bucket = max(int(bucket_bytes) // 4, 1)
    buckets = []
    start, total = 0, 0
    for i, size in enumerate(leaves):
        total += int(size)
        if total >= per_bucket:
            padded, _ = quantized_allreduce_sizes(total, world, chunk_size)
            buckets.append((slice(start, i + 1), total, padded))
            start, total = i + 1, 0
    if total > 0 or not buckets:
        total = max(total, 1)
        padded, _ = quantized_allreduce_sizes(total, world, chunk_size)
        buckets.append((slice(start, len(leaves)), total, padded))
    return buckets


def init_residuals(grads, world, bucket_bytes, chunk_size):
    """Zero error-feedback state for :func:`quantized_allreduce_tree` over
    a gradient pytree: per bucket, a ``[world, padded_n]`` worker residual
    stack (row r lives on rank r) and a ``[world, padded_n/world]`` server
    stack (row r is the shard rank r serves)."""
    leaves = jax.tree_util.tree_leaves(grads)
    plan = bucket_plan([l.size for l in leaves], world, bucket_bytes,
                       chunk_size)
    return {
        "worker": [jnp.zeros((world, padded), jnp.float32)
                   for _, _, padded in plan],
        "server": [jnp.zeros((world, padded // world), jnp.float32)
                   for _, _, padded in plan],
    }


def quantized_allreduce_tree(grads, axis_name, chunk_size=512,
                             bucket_bytes=4 * 1024 * 1024, residuals=None):
    """Bucketed int8 averaging all-reduce of a gradient pytree.

    Flattens the tree, packs leaves into ~``bucket_bytes`` buckets
    (:func:`bucket_plan`), and runs one :func:`quantized_allreduce` per
    bucket — independent collective chains XLA can overlap with
    neighbouring compute. ``residuals`` is the (shard_map-local) state
    from :func:`init_residuals` rows, i.e. per-bucket ``worker`` [padded]
    and ``server`` [padded/world] vectors, or ``None`` for no EF.

    Returns ``(avg_tree, new_residuals)``."""
    world = axis_size(axis_name)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    plan = bucket_plan([l.size for l in leaves], world, bucket_bytes,
                       chunk_size)

    out_leaves = [None] * len(leaves)
    new_res = {"worker": [], "server": []} if residuals is not None else None
    for b, (sl, n, padded) in enumerate(plan):
        members = leaves[sl]
        flat = jnp.concatenate(
            [m.reshape(-1).astype(jnp.float32) for m in members]) \
            if len(members) > 1 else members[0].reshape(-1).astype(jnp.float32)
        if padded > n:
            flat = jnp.pad(flat, (0, padded - n))
        we = residuals["worker"][b] if residuals is not None else None
        se = residuals["server"][b] if residuals is not None else None
        avg, we2, se2 = quantized_allreduce(
            flat, axis_name, chunk_size=chunk_size,
            worker_residual=we, server_residual=se)
        if new_res is not None:
            new_res["worker"].append(we2)
            new_res["server"].append(se2)
        off = 0
        for j, m in zip(range(sl.start, sl.stop), members):
            out_leaves[j] = avg[off:off + m.size].reshape(m.shape)
            off += m.size
    return jax.tree_util.tree_unflatten(treedef, out_leaves), new_res
