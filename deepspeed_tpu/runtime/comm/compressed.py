"""Error-feedback 1-bit compressed allreduce, TPU-native.

Capability parity with the reference's ``Compressed_Allreduce``
(`runtime/fp16/onebit_adam.py:104-228`) and its MPI/cupy data plane
(`runtime/custom_collectives.py:23-153`), re-designed as an XLA collective:

- sign/scale compression and bit-packing run on-device (VPU elementwise +
  an 8-wide dot against powers of two replacing ``cupy.packbits``);
- the 2-phase "gather to chunk-server, server-reduce, allgather" MPI
  topology becomes one ``all_to_all`` + one ``all_gather`` over a named
  mesh axis inside ``shard_map`` — each rank is the server for its 1/world
  chunk, exactly like the reference's rank-owned chunks;
- worker and server error-feedback residuals are carried by the caller as
  explicit state (the reference stashes them on the optimizer,
  onebit_adam.py:305-308).

Wire volume per device is ~n/4 bytes (packed signs both ways + scalars) vs
8n bytes for an fp32 ring allreduce — the reference's headline "up to 5x
less communication" (README.md:19,40).

All functions here are pure and must be called inside ``shard_map`` with
``axis_name`` bound (tests drive them over the 8-device CPU mesh).
"""

import functools

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.collectives import (
    gather_from_chunk_servers, scatter_to_chunk_servers)
from deepspeed_tpu.utils.compat import axis_size

__all__ = ["pack_signs", "unpack_signs", "compressed_allreduce",
           "error_feedback_sizes"]

_POW2 = tuple(1 << i for i in range(8))


def pack_signs(signs):
    """Pack a [..., n] bool array (True = +1) into [..., n//8] uint8.

    ``n`` must be a multiple of 8. The analog of ``cupy.packbits``
    (`custom_collectives.py:33`), expressed as a reshape + small dot so XLA
    lowers it to vectorized integer ops.
    """
    *lead, n = signs.shape
    assert n % 8 == 0, f"pack_signs needs n % 8 == 0, got {n}"
    bits = signs.reshape(*lead, n // 8, 8).astype(jnp.uint8)
    weights = jnp.asarray(_POW2, jnp.uint8)
    return (bits * weights).sum(axis=-1).astype(jnp.uint8)


def unpack_signs(packed, dtype=jnp.float32):
    """Inverse of :func:`pack_signs`: [..., m] uint8 → [..., 8*m] ±1."""
    *lead, m = packed.shape
    weights = jnp.asarray(_POW2, jnp.uint8)
    bits = (packed[..., None] & weights) > 0
    pm1 = jnp.where(bits, jnp.asarray(1, jnp.int8), jnp.asarray(-1, jnp.int8))
    return pm1.reshape(*lead, m * 8).astype(dtype)


def _compress(x, n_valid):
    """sign+scale compression: returns (packed_signs, scale, residual).

    ``scale = ||x||_2 / sqrt(n_valid)`` (reference onebit_adam.py:122-139);
    the residual is the error-feedback term ``x - scale * sign(x)`` with
    any padding region zeroed so dead elements never accumulate error.
    """
    n = x.shape[-1]
    valid = (jnp.arange(n) < n_valid)
    x = jnp.where(valid, x, 0.0)
    scale = jnp.linalg.norm(x) / jnp.sqrt(jnp.asarray(n_valid, x.dtype))
    signs = x >= 0
    sgn = jnp.where(signs, 1.0, -1.0).astype(x.dtype)
    residual = jnp.where(valid, x - scale * sgn, 0.0)
    return pack_signs(signs), scale, residual


def error_feedback_sizes(n, world):
    """(padded_n, chunk) for an n-element buffer over a world-size axis.

    Padding aligns to ``8 * world`` so every per-rank chunk packs to whole
    bytes (the reference pads to ``world`` divisibility the same way,
    onebit_adam.py:117-121, plus cupy's byte alignment).
    """
    align = 8 * world
    padded = ((n + align - 1) // align) * align
    return padded, padded // world


def compressed_allreduce(x, worker_error, server_error, axis_name,
                         n_valid=None):
    """1-bit error-feedback averaging allreduce of ``x`` over ``axis_name``.

    Must run inside ``shard_map``. Per rank:
      ``x``            [padded_n]  local vector to average (padding zeroed)
      ``worker_error`` [padded_n]  this rank's compression residual
      ``server_error`` [chunk]     residual for the chunk this rank serves

    Returns ``(avg, new_worker_error, new_server_error)`` where ``avg`` is
    the doubly-compressed average — identical on every rank, like the
    reference's final allgather (onebit_adam.py:200-228).
    """
    world = axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    padded_n = x.shape[-1]
    chunk = padded_n // world
    assert chunk * world == padded_n and chunk % 8 == 0, (
        f"buffer of {padded_n} not aligned for world {world}; "
        f"use error_feedback_sizes()")
    if n_valid is None:
        n_valid = padded_n

    # Phase 1 — worker compression (reference 122-139).
    corrected = x + worker_error
    packed, scale, new_worker_error = _compress(corrected, n_valid)

    # Phase 2 — exchange: rank r receives every rank's packed chunk r
    # (the reference's igather to chunk servers, custom_collectives.py:23;
    # the same chunk-server scatter the int8 path in `comm/quantized.py`
    # rides, factored into `parallel/collectives.py`).
    packed = packed.reshape(world, chunk // 8)
    recv = scatter_to_chunk_servers(packed, axis_name)       # [world, chunk/8]
    scales = gather_from_chunk_servers(scale, axis_name)     # [world]

    # Phase 3 — server reduce + second compression (reference 160-199).
    decoded = unpack_signs(recv) * scales[:, None]           # [world, chunk]
    chunk_avg = decoded.mean(axis=0) + server_error
    # Validity mask for this rank's chunk within the original n_valid.
    chunk_valid = jnp.clip(n_valid - rank * chunk, 0, chunk)
    n_csafe = jnp.maximum(chunk_valid, 1)
    valid = jnp.arange(chunk) < chunk_valid
    chunk_avg = jnp.where(valid, chunk_avg, 0.0)
    s_scale = jnp.linalg.norm(chunk_avg) / jnp.sqrt(
        n_csafe.astype(chunk_avg.dtype))
    s_signs = chunk_avg >= 0
    s_sgn = jnp.where(s_signs, 1.0, -1.0).astype(chunk_avg.dtype)
    new_server_error = jnp.where(valid, chunk_avg - s_scale * s_sgn, 0.0)

    # Phase 4 — allgather the served chunks (reference 200-228).
    all_packed, all_scales = gather_from_chunk_servers(
        (pack_signs(s_signs), s_scale), axis_name)           # [world, ...]
    avg = (unpack_signs(all_packed) *
           all_scales[:, None]).reshape(padded_n)
    avg = jnp.where(jnp.arange(padded_n) < n_valid, avg, 0.0)
    return avg, new_worker_error, new_server_error
