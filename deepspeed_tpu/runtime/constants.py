"""Config keys and defaults.

Mirrors the key/default surface of the reference's
``deepspeed/runtime/constants.py`` (306 LoC of CONSTANT/CONSTANT_DEFAULT
pairs) so that an existing DeepSpeed JSON config is accepted unchanged, with
TPU-specific additions (precision policy, mesh shape) at the bottom.
"""

#############################################
# Routes
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer and lr scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

# Steps
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

# Sparse gradients (embedding-style CSR reduction)
SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

#############################################
# FP16 (on TPU: low-precision policy; bf16 needs no loss scaling)
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

#############################################
# BF16 (TPU-native low precision; extension over the reference)
#############################################
BF16 = "bf16"
BF16_ENABLED = "enabled"
BF16_ENABLED_DEFAULT = False

#############################################
# AMP (accepted for config compat; maps onto the bf16 policy on TPU)
#############################################
AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

#############################################
# Gradient clipping / allreduce knobs
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

FP32_ALLREDUCE = "fp32_allreduce"
FP32_ALLREDUCE_DEFAULT = False

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

VOCABULARY_SIZE = "vocabulary_size"
VOCABULARY_SIZE_DEFAULT = None

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

#############################################
# Tensorboard
#############################################
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedTpuJobName"

#############################################
# Progressive Layer Drop (PLD)
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Sparse attention
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

#############################################
# Pipeline parallelism
#############################################
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_STAGES_DEFAULT = None
PIPELINE_PARTITION = "partition"
PIPELINE_PARTITION_DEFAULT = "best"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_SEED_LAYERS_DEFAULT = False
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT = 0

#############################################
# TPU-specific additions (not in the reference)
#############################################
# Mesh shape: named axes -> sizes, e.g. {"data": 8, "model": 1}.
# Axes: data / model / pipe / seq / expert. Unspecified axes default to 1;
# a data axis of None absorbs the remaining devices.
MESH = "mesh"
MESH_DEFAULT = None

# 1-bit Adam comm compression (reference: runtime/fp16/onebit_adam.py)
ONEBIT_ADAM_FREEZE_STEP = "freeze_step"
ONEBIT_ADAM_FREEZE_STEP_DEFAULT = 100000

# Int8 quantized gradient all-reduce (EQuARX-style; runtime/comm/quantized.py).
# Chunk-wise absmax-scaled int8 reduce-scatter + all-gather for the dense-DP /
# ZeRO-1/2 gradient sync, with optional error-feedback residuals and
# fixed-byte bucketing for backward overlap.
COMM_QUANTIZATION = "comm_quantization"
COMM_QUANTIZATION_ENABLED = "enabled"
COMM_QUANTIZATION_ENABLED_DEFAULT = False
COMM_QUANTIZATION_BITS = "bits"
COMM_QUANTIZATION_BITS_DEFAULT = 8
COMM_QUANTIZATION_CHUNK_SIZE = "chunk_size"
COMM_QUANTIZATION_CHUNK_SIZE_DEFAULT = 512
COMM_QUANTIZATION_BUCKET_MB = "bucket_mb"
COMM_QUANTIZATION_BUCKET_MB_DEFAULT = 4
COMM_QUANTIZATION_ERROR_FEEDBACK = "error_feedback"
COMM_QUANTIZATION_ERROR_FEEDBACK_DEFAULT = False

# Resilience subsystem (runtime/resilience/): preemption-safe checkpointing,
# auto-resume, step health guards, fault injection. See docs/resilience.md.
RESILIENCE = "resilience"
RESILIENCE_AUTO_RESUME = "auto_resume"
RESILIENCE_AUTO_RESUME_DEFAULT = False
RESILIENCE_SAVE_DIR = "save_dir"
RESILIENCE_SAVE_DIR_DEFAULT = None
RESILIENCE_SAVE_INTERVAL_STEPS = "save_interval_steps"
RESILIENCE_SAVE_INTERVAL_STEPS_DEFAULT = 0  # 0 = no periodic saves

RESILIENCE_CHECKPOINT = "checkpoint"
RESILIENCE_CKPT_ASYNC_SAVE = "async_save"
RESILIENCE_CKPT_ASYNC_SAVE_DEFAULT = False
RESILIENCE_CKPT_KEEP_LAST_N = "keep_last_n"
RESILIENCE_CKPT_KEEP_LAST_N_DEFAULT = 0  # 0 = keep everything
RESILIENCE_CKPT_IO_RETRIES = "io_retries"
RESILIENCE_CKPT_IO_RETRIES_DEFAULT = 3
RESILIENCE_CKPT_IO_RETRY_BASE_S = "io_retry_base_s"
RESILIENCE_CKPT_IO_RETRY_BASE_S_DEFAULT = 0.05
RESILIENCE_CKPT_IO_TIMEOUT_S = "io_timeout_s"
RESILIENCE_CKPT_IO_TIMEOUT_S_DEFAULT = None  # None = no deadline

RESILIENCE_GUARDS = "guards"
RESILIENCE_GUARD_ACTION = "action"
RESILIENCE_GUARD_NAN = "nan_grads"
RESILIENCE_GUARD_NAN_ACTION_DEFAULT = None  # disabled
RESILIENCE_GUARD_LOSS_SPIKE = "loss_spike"
RESILIENCE_GUARD_LOSS_SPIKE_ACTION_DEFAULT = None  # disabled
RESILIENCE_GUARD_LOSS_SPIKE_WINDOW = "window"
RESILIENCE_GUARD_LOSS_SPIKE_WINDOW_DEFAULT = 20
RESILIENCE_GUARD_LOSS_SPIKE_FACTOR = "factor"
RESILIENCE_GUARD_LOSS_SPIKE_FACTOR_DEFAULT = 10.0
RESILIENCE_GUARD_LOSS_SPIKE_MIN_HISTORY = "min_history"
RESILIENCE_GUARD_LOSS_SPIKE_MIN_HISTORY_DEFAULT = 5
RESILIENCE_GUARD_SCALE_COLLAPSE = "scale_collapse"
RESILIENCE_GUARD_SCALE_COLLAPSE_ACTION_DEFAULT = None  # disabled
RESILIENCE_GUARD_SCALE_COLLAPSE_PATIENCE = "patience"
RESILIENCE_GUARD_SCALE_COLLAPSE_PATIENCE_DEFAULT = 10

RESILIENCE_PREEMPTION = "preemption"
RESILIENCE_PREEMPTION_SAVE_ON_SIGTERM = "save_on_sigterm"
RESILIENCE_PREEMPTION_SAVE_ON_SIGTERM_DEFAULT = False

RESILIENCE_FAULT_INJECTION = "fault_injection"
RESILIENCE_FAULT_INJECTION_ENABLED = "enabled"
RESILIENCE_FAULT_INJECTION_ENABLED_DEFAULT = False

# In-memory hot-checkpoint tier (runtime/resilience/hotckpt.py):
# frequent CRC-stamped device->host snapshots the restore ladder tries
# before any disk checkpoint. interval_steps = 0 disables the tier.
RESILIENCE_HOT_CHECKPOINT = "hot_checkpoint"
RESILIENCE_HOT_ENABLED = "enabled"
RESILIENCE_HOT_ENABLED_DEFAULT = False
RESILIENCE_HOT_INTERVAL_STEPS = "interval_steps"
RESILIENCE_HOT_INTERVAL_STEPS_DEFAULT = 1
RESILIENCE_HOT_CAPACITY = "capacity"
RESILIENCE_HOT_CAPACITY_DEFAULT = 1
RESILIENCE_HOT_MIRROR_DIR = "mirror_dir"
RESILIENCE_HOT_MIRROR_DIR_DEFAULT = None  # None = RAM-only tier
RESILIENCE_HOT_MIRROR_KEEP = "mirror_keep"
RESILIENCE_HOT_MIRROR_KEEP_DEFAULT = 1

RESILIENCE_HOST_ADAM_RETRIES = "host_adam_retries"
RESILIENCE_HOST_ADAM_RETRIES_DEFAULT = 2

# Elasticity (runtime/elastic/): topology-agnostic checkpoints,
# reshard-on-resume across data-parallel world sizes, and the elastic
# batch solver that re-derives micro x grad_accum to preserve the
# effective batch. See docs/elasticity.md.
ELASTICITY = "elasticity"
ELASTICITY_ENABLED = "enabled"
ELASTICITY_ENABLED_DEFAULT = False
ELASTICITY_TARGET_GLOBAL_BATCH = "target_global_batch"
ELASTICITY_TARGET_GLOBAL_BATCH_DEFAULT = None  # None = train_batch_size
ELASTICITY_MAX_WORLD_SIZE = "max_world_size"
ELASTICITY_MAX_WORLD_SIZE_DEFAULT = 0  # 0 = unbounded
ELASTICITY_STRICT = "strict"
ELASTICITY_STRICT_DEFAULT = False
ELASTICITY_LR_SCALING = "lr_scaling"
ELASTICITY_LR_SCALING_DEFAULT = "linear"  # linear | sqrt | none

# Compiled-program analysis (deepspeed_tpu/analysis): opt-in audits of
# the compiled train step's HLO at compile time — donation/aliasing,
# ZeRO byte budgets, dtype hygiene, host transfers, trip-count
# accounting — plus a per-step recompile detector. See docs/analysis.md.
ANALYSIS = "analysis"
ANALYSIS_ENABLED = "enabled"
ANALYSIS_ENABLED_DEFAULT = False
ANALYSIS_FAIL_ON_FINDINGS = "fail_on_findings"
ANALYSIS_FAIL_ON_FINDINGS_DEFAULT = False
ANALYSIS_RULES = "rules"
ANALYSIS_RULES_DEFAULT = None  # None = the full rule catalog
ANALYSIS_CHECK_RECOMPILE = "check_recompile"
ANALYSIS_CHECK_RECOMPILE_DEFAULT = True
# Explicit per-device peak-memory budget (MB) for the `peak_memory`
# rule; 0 derives a generous per-ZeRO-stage default from the model's
# fp32 master footprint (see analysis/rules.py:rule_peak_memory).
ANALYSIS_PEAK_MEMORY_BUDGET_MB = "peak_memory_budget_mb"
ANALYSIS_PEAK_MEMORY_BUDGET_MB_DEFAULT = 0
# Cost-model constants table for the autotuner (`ds_tpu_tune`) and any
# roofline estimate derived from this config; must name a row of
# analysis.cost.PLATFORMS.
ANALYSIS_PLATFORM = "platform"
ANALYSIS_PLATFORM_DEFAULT = "tpu_v5e"

# Manual tensor-parallel tuning (parallel/pipe_tp.py, parallel/sequence.py,
# moe/expert_pipe.py). The `overlap` block enables the latency-hiding
# collective matmul: row-parallel combines / Ulysses all_to_all brackets
# are split into `chunks` pieces whose ppermute rings software-pipeline
# against the adjacent matmuls (parallel/collectives.py). Per-site
# overrides under `sites` keyed by parallel.collectives.OVERLAP_SITES.
# See docs/tensor-parallel.md.
TENSOR_PARALLEL = "tensor_parallel"
TP_OVERLAP = "overlap"
TP_OVERLAP_ENABLED = "enabled"
TP_OVERLAP_ENABLED_DEFAULT = False
TP_OVERLAP_CHUNKS = "chunks"
TP_OVERLAP_CHUNKS_DEFAULT = 4
TP_OVERLAP_BIDIRECTIONAL = "bidirectional"
TP_OVERLAP_BIDIRECTIONAL_DEFAULT = False
TP_OVERLAP_SITES = "sites"
TP_OVERLAP_SITES_DEFAULT = None  # None = no per-site overrides
# Quantized-wire codec for the overlap rings ("int8" / "f8e4m3fn" /
# "f8e5m2"; None = full-precision wire). Chunk payloads + per-chunk f32
# scales ride the same ppermute; chunks=1 routes through the bracketed
# quantize→monolithic-collective reference. See docs/fp8.md.
TP_OVERLAP_WIRE_DTYPE = "wire_dtype"
TP_OVERLAP_WIRE_DTYPE_DEFAULT = None
TP_OVERLAP_WIRE_CHUNK = "wire_chunk"
TP_OVERLAP_WIRE_CHUNK_DEFAULT = 512

# fp8 end-to-end training (ops/fp8.py + the quantized collective wire;
# docs/fp8.md). `enabled` turns the GPT-2 Dense matmuls into delayed-
# scaling fp8 GEMMs (f8e4m3fn forward operands, f8e5m2 backward
# cotangents, amax histories carried as engine state); the `wire` block
# quantizes the ring collectives' payloads through the codec registry
# (runtime/comm/codecs.py) — including ZeRO-3 gathers.
FP8 = "fp8"
FP8_ENABLED = "enabled"
FP8_ENABLED_DEFAULT = False
FP8_MARGIN = "margin"
FP8_MARGIN_DEFAULT = 0
FP8_AMAX_HISTORY_LEN = "amax_history_len"
FP8_AMAX_HISTORY_LEN_DEFAULT = 16
FP8_SITES = "sites"
FP8_SITES_DEFAULT = None         # None = no per-site overrides
FP8_WIRE = "wire"
FP8_WIRE_ENABLED = "enabled"
FP8_WIRE_ENABLED_DEFAULT = False
FP8_WIRE_DTYPE = "dtype"
FP8_WIRE_DTYPE_DEFAULT = "f8e4m3fn"
FP8_WIRE_CHUNK_SIZE = "chunk_size"
FP8_WIRE_CHUNK_SIZE_DEFAULT = 512

# Runtime telemetry (deepspeed_tpu/telemetry): structured metrics
# registry, step-phase spans, and the schema-versioned JSONL event log
# the ds_tpu_metrics CLI reads. Disabled by default — the engine's hot
# path then pays one no-op check per phase. See docs/observability.md.
TELEMETRY = "telemetry"
TELEMETRY_ENABLED = "enabled"
TELEMETRY_ENABLED_DEFAULT = False
TELEMETRY_JSONL_PATH = "jsonl_path"
TELEMETRY_JSONL_PATH_DEFAULT = None  # None = in-memory ring only
TELEMETRY_CONSOLE = "console"
TELEMETRY_CONSOLE_DEFAULT = False
TELEMETRY_PROMETHEUS_TEXTFILE = "prometheus_textfile"
TELEMETRY_PROMETHEUS_TEXTFILE_DEFAULT = None
TELEMETRY_PROMETHEUS_WRITE_EVERY = "prometheus_write_every"
TELEMETRY_PROMETHEUS_WRITE_EVERY_DEFAULT = 20
# Bounded event ring (engine.metrics_history): last N step events kept
# in memory so tests/health guards can assert without file I/O.
TELEMETRY_HISTORY = "history"
TELEMETRY_HISTORY_DEFAULT = 256
# Stamp compile-time static facts (collective bytes/counts, static peak
# memory) into one `compile` event. Free when the analysis block already
# audited the step; otherwise costs one extra lowering at first compile.
TELEMETRY_STAMP_STATIC_FACTS = "stamp_static_facts"
TELEMETRY_STAMP_STATIC_FACTS_DEFAULT = True
# Model flops per token for the MFU estimate (0 = unknown; the
# ds_tpu_metrics CLI can also supply it at read time).
TELEMETRY_FLOPS_PER_TOKEN = "flops_per_token"
TELEMETRY_FLOPS_PER_TOKEN_DEFAULT = 0

# Runtime forensics (telemetry/flight.py, telemetry/watchdog.py):
# setting crash_dump_dir turns on the flight recorder — a bounded
# black-box ring of events / span transitions / collective confessions
# dumped atomically there (plus all-thread stacks) on unhandled
# exception, SIGTERM/SIGQUIT, guard-trip abort, or watchdog firing.
# It also holds per-process heartbeat files and watchdog dumps, so the
# nested watchdog block requires it. See docs/observability.md.
TELEMETRY_CRASH_DUMP_DIR = "crash_dump_dir"
TELEMETRY_CRASH_DUMP_DIR_DEFAULT = None
TELEMETRY_FLIGHT_HISTORY = "flight_history"
TELEMETRY_FLIGHT_HISTORY_DEFAULT = 512
# Hang watchdog: daemon thread fed per-phase heartbeats from the span
# stack; fires when a step's elapsed wall exceeds
# max(min_deadline_s, deadline_factor * rolling-median step wall).
TELEMETRY_WATCHDOG = "watchdog"
TELEMETRY_WATCHDOG_ENABLED = "enabled"
TELEMETRY_WATCHDOG_ENABLED_DEFAULT = False
TELEMETRY_WATCHDOG_DEADLINE_FACTOR = "deadline_factor"
TELEMETRY_WATCHDOG_DEADLINE_FACTOR_DEFAULT = 3.0
TELEMETRY_WATCHDOG_MIN_DEADLINE_S = "min_deadline_s"
TELEMETRY_WATCHDOG_MIN_DEADLINE_S_DEFAULT = 60.0
# "dump" = flight dump once per hung step, run continues if the step
# ever completes; "abort" = dump + thread stacks + SIGABRT so a cluster
# supervisor restarts the process.
TELEMETRY_WATCHDOG_ACTION = "action"
TELEMETRY_WATCHDOG_ACTION_DEFAULT = "dump"
# Anomaly-triggered trace capture: a step-wall regression past factor x
# rolling median (or a recompile / guard trip) arms the profiling
# block's TraceProfiler to capture the next capture_steps steps.
TELEMETRY_ANOMALY_TRACE = "anomaly_trace"
TELEMETRY_ANOMALY_TRACE_ENABLED = "enabled"
TELEMETRY_ANOMALY_TRACE_ENABLED_DEFAULT = False
TELEMETRY_ANOMALY_TRACE_FACTOR = "factor"
TELEMETRY_ANOMALY_TRACE_FACTOR_DEFAULT = 2.0
TELEMETRY_ANOMALY_TRACE_WINDOW = "window"
TELEMETRY_ANOMALY_TRACE_WINDOW_DEFAULT = 32
TELEMETRY_ANOMALY_TRACE_CAPTURE_STEPS = "capture_steps"
TELEMETRY_ANOMALY_TRACE_CAPTURE_STEPS_DEFAULT = 3

#############################################
# Inference / serving (deepspeed_tpu/inference/)
#############################################
# The jitted autoregressive serving engine: one chunked-prefill
# program + one decode program over a bucketed ring-buffer KV cache,
# driven by a host-side continuous-batching scheduler. See
# docs/inference.md.
INFERENCE = "inference"

# Rows in the KV cache = the compiled decode batch. Every decode step
# runs all rows; inactive rows are padding.
INFERENCE_MAX_BATCH = "max_batch"
INFERENCE_MAX_BATCH_DEFAULT = 8

# Per-request sequence-length budgets (host-side admission control,
# NOT compiled shapes): a request is assigned the smallest bucket that
# fits prompt + max_new_tokens and is evicted at the bucket edge. The
# cache buffer is sized to max(seq_buckets). Every bucket must be a
# multiple of prefill_chunk.
INFERENCE_SEQ_BUCKETS = "seq_buckets"
INFERENCE_SEQ_BUCKETS_DEFAULT = (128, 512)

# Prompts prefill in fixed [1, prefill_chunk] chunks so prompt length
# never reaches a jit boundary.
INFERENCE_PREFILL_CHUNK = "prefill_chunk"
INFERENCE_PREFILL_CHUNK_DEFAULT = 32

# KV cache storage: None = model compute dtype; "bf16"/"f32" = plain
# storage; a codec name from runtime/comm/codecs.py ("int8",
# "f8e4m3fn", "f8e5m2") = quantized storage with per-(row, position,
# head) f32 absmax scales.
INFERENCE_KV_CACHE_DTYPE = "kv_cache_dtype"
INFERENCE_KV_CACHE_DTYPE_DEFAULT = None

# Default generation budget for requests that don't specify one.
INFERENCE_MAX_NEW_TOKENS = "max_new_tokens"
INFERENCE_MAX_NEW_TOKENS_DEFAULT = 64

# Decode attention implementation: "dense" = full-cache softmax (the
# parity oracle), "flash" = the Pallas split-K flash-decode kernel
# (ops/pallas/flash_decode.py) with active-length block skipping and
# in-kernel KV dequantization. Prefill always runs dense.
INFERENCE_ATTENTION_IMPL = "attention_impl"
INFERENCE_ATTENTION_IMPL_DEFAULT = "dense"

# Flash-decode KV block size: the kernel streams the cache row in
# [block_k, head_dim] blocks. Clamped to max(seq_buckets), which it
# must divide.
INFERENCE_ATTENTION_BLOCK_K = "attention_block_k"
INFERENCE_ATTENTION_BLOCK_K_DEFAULT = 128

# In-program sampling knobs (static: they select the traced decode
# graph). temperature 0.0 = greedy argmax (consumes no randomness);
# top_k 0 and top_p 1.0 disable those filters.
INFERENCE_TEMPERATURE = "temperature"
INFERENCE_TEMPERATURE_DEFAULT = 0.0
INFERENCE_TOP_K = "top_k"
INFERENCE_TOP_K_DEFAULT = 0
INFERENCE_TOP_P = "top_p"
INFERENCE_TOP_P_DEFAULT = 1.0
INFERENCE_SAMPLING_SEED = "sampling_seed"
INFERENCE_SAMPLING_SEED_DEFAULT = 0

# KV cache layout: "ring" = one [max_batch, max_seq] row per request;
# "paged" = one [n_pages, page_size] pool per layer addressed through
# per-row page tables (host-side allocator + radix prefix cache +
# host-RAM tier for parked sessions, inference/paging.py). Both keep
# the 2-compile contract; paged decouples capacity from max_batch *
# max_seq.
INFERENCE_KV_LAYOUT = "kv_layout"
INFERENCE_KV_LAYOUT_DEFAULT = "ring"

# Tokens per page (paged layout). 0 = auto (two prefill chunks).
# Must be a multiple of prefill_chunk and divide max(seq_buckets);
# flash block_k clamps to it.
INFERENCE_PAGE_SIZE = "page_size"
INFERENCE_PAGE_SIZE_DEFAULT = 0

# Physical pages in the pool (paged layout). 0 = auto: ring-capacity
# parity (max_batch * max_seq / page_size) + the reserved trash page.
# Smaller pools trade admission headroom for HBM — the bench A/B and
# the tuner explore this.
INFERENCE_N_PAGES = "n_pages"
INFERENCE_N_PAGES_DEFAULT = 0

# Radix-tree prefix cache (paged layout): admissions whose prompt
# prefix matches interned pages map them copy-on-write and skip the
# shared span's prefill chunks.
INFERENCE_PREFIX_CACHE = "prefix_cache"
INFERENCE_PREFIX_CACHE_DEFAULT = True

# Host-RAM tier pressure threshold (paged layout): while free pages /
# n_pages sits below this fraction, parked sessions' pages are
# evacuated to host RAM (LRU first). 0.0 disables proactive
# evacuation (pressure-driven eviction still runs on exhaustion).
INFERENCE_HOST_PARK_THRESHOLD = "host_park_threshold"
INFERENCE_HOST_PARK_THRESHOLD_DEFAULT = 0.25

# Serving fleet (ISSUE 17): N replica workers behind one admission
# router with drain/redispatch on replica death. replicas=1 keeps the
# single-engine path.
INFERENCE_REPLICAS = "replicas"
INFERENCE_REPLICAS_DEFAULT = 1

# Redispatches a request survives before the router aborts it with the
# typed RequestAbortedError / "aborted" finish reason.
INFERENCE_MAX_REDISPATCH = "max_redispatch"
INFERENCE_MAX_REDISPATCH_DEFAULT = 2

# Per-replica in-flight bound: the router defers dispatch (fleet_defer)
# while every healthy replica is at it.
INFERENCE_MAX_QUEUE_DEPTH = "max_queue_depth"
INFERENCE_MAX_QUEUE_DEPTH_DEFAULT = 8

# Per-request wall-clock bounds (seconds; 0 disables): total budget
# from submit to completion, and queue wait before admission. Either
# expiry finishes the request with the typed "timeout" reason.
INFERENCE_DEADLINE_S = "deadline_s"
INFERENCE_DEADLINE_S_DEFAULT = 0.0
INFERENCE_QUEUE_TIMEOUT_S = "queue_timeout_s"
INFERENCE_QUEUE_TIMEOUT_S_DEFAULT = 0.0

# Disaggregated prefill/decode serving (inference.disaggregated): the
# admission router splits the fleet into a PREFILL tier (workers that
# only run the prefill program, writing paged KV) and a DECODE tier
# (workers that only run the decode step), moving finished prompts
# between them through an explicit KV-page handoff. Each tier pins
# exactly one compiled program; tiers scale independently
# (prefill_workers x decode_workers, each with its own max_batch —
# 0 falls back to the shared max_batch). Requires kv_layout="paged".
INFERENCE_DISAGGREGATED = "disaggregated"
INFERENCE_DISAGGREGATED_DEFAULT = False
INFERENCE_PREFILL_WORKERS = "prefill_workers"
INFERENCE_PREFILL_WORKERS_DEFAULT = 1
INFERENCE_DECODE_WORKERS = "decode_workers"
INFERENCE_DECODE_WORKERS_DEFAULT = 1
INFERENCE_PREFILL_MAX_BATCH = "prefill_max_batch"
INFERENCE_PREFILL_MAX_BATCH_DEFAULT = 0
INFERENCE_DECODE_MAX_BATCH = "decode_max_batch"
INFERENCE_DECODE_MAX_BATCH_DEFAULT = 0

# Speculative decoding (inference.speculative sub-block): a
# self-speculative draft of `k` tokens through the first `draft_layers`
# blocks of the SAME model (truncated scan — no second weight set),
# verified in one full-depth teacher-forced program. The serving
# compile contract becomes 3 pinned programs (prefill, draft, verify).
# draft_layers=0 auto-selects n_layer // 2; min_accept_to_grow > 0
# turns on the adaptive draft-length controller (grow toward k while
# mean acceptance clears the threshold, shrink otherwise).
INFERENCE_SPECULATIVE = "speculative"
INFERENCE_SPECULATIVE_ENABLED = "enabled"
INFERENCE_SPECULATIVE_ENABLED_DEFAULT = False
INFERENCE_SPECULATIVE_K = "k"
INFERENCE_SPECULATIVE_K_DEFAULT = 4
INFERENCE_SPECULATIVE_DRAFT_LAYERS = "draft_layers"
INFERENCE_SPECULATIVE_DRAFT_LAYERS_DEFAULT = 0
INFERENCE_SPECULATIVE_MIN_ACCEPT_TO_GROW = "min_accept_to_grow"
INFERENCE_SPECULATIVE_MIN_ACCEPT_TO_GROW_DEFAULT = 0.0
