"""DeepSpeed-compatible JSON/dict config → typed config object.

TPU-native analog of the reference's ``DeepSpeedConfig``
(`runtime/config.py:485`): same key surface, same batch-size triple solver
(``train_batch_size = micro_batch * grad_accum * dp_world_size``,
`runtime/config.py:586-632`), same error checks (`runtime/config.py:657`),
plus a TPU ``mesh`` section describing the named device-mesh axes that
replace the reference's process groups.
"""

import json
import logging

from deepspeed_tpu.runtime.constants import *  # noqa: F401,F403
from deepspeed_tpu.runtime.config_utils import (
    get_scalar_param,
    dict_raise_error_on_duplicate_keys,
)
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.constants import (
    MAX_STAGE_ZERO_OPTIMIZATION,
    ZERO_OPTIMIZATION,
)
from deepspeed_tpu.runtime.activation_checkpointing.config import (
    DeepSpeedActivationCheckpointingConfig,
)
from deepspeed_tpu.utils.logging import logger

TENSOR_CORE_ALIGN_SIZE = 8
ADAM_OPTIMIZER = "adam"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
DEEPSPEED_OPTIMIZERS = [ADAM_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER]


def get_fp16_enabled(param_dict):
    if FP16 in param_dict:
        return get_scalar_param(param_dict[FP16], FP16_ENABLED, FP16_ENABLED_DEFAULT)
    return False


def get_bf16_enabled(param_dict):
    if BF16 in param_dict:
        return get_scalar_param(param_dict[BF16], BF16_ENABLED, BF16_ENABLED_DEFAULT)
    return False


def get_amp_enabled(param_dict):
    if AMP in param_dict:
        return get_scalar_param(param_dict[AMP], AMP_ENABLED, AMP_ENABLED_DEFAULT)
    return False


def get_amp_params(param_dict):
    if AMP in param_dict:
        amp_params = dict(param_dict[AMP])
        amp_params.pop(AMP_ENABLED, None)
        return amp_params
    return False


def get_loss_scale(param_dict):
    if get_fp16_enabled(param_dict):
        return get_scalar_param(param_dict[FP16], FP16_LOSS_SCALE,
                                FP16_LOSS_SCALE_DEFAULT)
    return FP16_LOSS_SCALE_DEFAULT


def get_initial_dynamic_scale(param_dict):
    if get_fp16_enabled(param_dict):
        initial_scale_power = get_scalar_param(param_dict[FP16],
                                               FP16_INITIAL_SCALE_POWER,
                                               FP16_INITIAL_SCALE_POWER_DEFAULT)
    else:
        initial_scale_power = FP16_INITIAL_SCALE_POWER_DEFAULT
    return 2 ** initial_scale_power


def get_dynamic_loss_scale_args(param_dict):
    loss_scale_args = None
    if get_fp16_enabled(param_dict):
        fp16_dict = param_dict[FP16]
        dynamic_keys = (FP16_INITIAL_SCALE_POWER, FP16_LOSS_SCALE_WINDOW,
                        FP16_MIN_LOSS_SCALE, FP16_HYSTERESIS)
        if any(k in fp16_dict for k in dynamic_keys):
            init_scale = get_scalar_param(fp16_dict,
                                          FP16_INITIAL_SCALE_POWER,
                                          FP16_INITIAL_SCALE_POWER_DEFAULT)
            scale_window = get_scalar_param(fp16_dict,
                                            FP16_LOSS_SCALE_WINDOW,
                                            FP16_LOSS_SCALE_WINDOW_DEFAULT)
            delayed_shift = get_scalar_param(fp16_dict,
                                             FP16_HYSTERESIS,
                                             FP16_HYSTERESIS_DEFAULT)
            min_loss_scale = get_scalar_param(fp16_dict,
                                              FP16_MIN_LOSS_SCALE,
                                              FP16_MIN_LOSS_SCALE_DEFAULT)
            loss_scale_args = {
                "init_scale": 2 ** init_scale,
                "scale_window": scale_window,
                "delayed_shift": delayed_shift,
                "min_scale": min_loss_scale,
            }
    return loss_scale_args


def get_gradient_accumulation_steps(param_dict):
    return get_scalar_param(param_dict, GRADIENT_ACCUMULATION_STEPS,
                            GRADIENT_ACCUMULATION_STEPS_DEFAULT)


def get_sparse_gradients_enabled(param_dict):
    return get_scalar_param(param_dict, SPARSE_GRADIENTS, SPARSE_GRADIENTS_DEFAULT)


def get_zero_optimization(param_dict):
    return ZERO_OPTIMIZATION in param_dict


def get_optimizer_name(param_dict):
    if OPTIMIZER in param_dict and TYPE in param_dict[OPTIMIZER]:
        return param_dict[OPTIMIZER][TYPE]
    return OPTIMIZER_TYPE_DEFAULT


def get_optimizer_params(param_dict):
    if get_optimizer_name(param_dict) is not None and \
            OPTIMIZER_PARAMS in param_dict[OPTIMIZER]:
        return param_dict[OPTIMIZER][OPTIMIZER_PARAMS]
    return None


def get_optimizer_legacy_fusion(param_dict):
    if OPTIMIZER in param_dict and LEGACY_FUSION in param_dict[OPTIMIZER]:
        return param_dict[OPTIMIZER][LEGACY_FUSION]
    return LEGACY_FUSION_DEFAULT


def get_scheduler_name(param_dict):
    if SCHEDULER in param_dict and TYPE in param_dict[SCHEDULER]:
        return param_dict[SCHEDULER][TYPE]
    return SCHEDULER_TYPE_DEFAULT


def get_scheduler_params(param_dict):
    if get_scheduler_name(param_dict) is not None and \
            SCHEDULER_PARAMS in param_dict[SCHEDULER]:
        return param_dict[SCHEDULER][SCHEDULER_PARAMS]
    return None


def get_pld_enabled(param_dict):
    if PROGRESSIVE_LAYER_DROP in param_dict:
        return get_scalar_param(param_dict[PROGRESSIVE_LAYER_DROP],
                                PLD_ENABLED, PLD_ENABLED_DEFAULT)
    return False


def get_pld_params(param_dict):
    if PROGRESSIVE_LAYER_DROP in param_dict:
        pld_params = dict(param_dict[PROGRESSIVE_LAYER_DROP])
        pld_params.pop(PLD_ENABLED, None)
        return pld_params
    return False


def get_sparse_attention(param_dict):
    """Parse the sparse_attention section into kwargs for a SparsityConfig.

    Mirrors the mode dispatch of the reference (`runtime/config.py:177-345`).
    """
    if SPARSE_ATTENTION not in param_dict:
        return None
    sparsity = param_dict[SPARSE_ATTENTION]
    mode = get_scalar_param(sparsity, SPARSE_MODE, SPARSE_MODE_DEFAULT)

    common = {
        SPARSE_MODE: mode,
        SPARSE_BLOCK: get_scalar_param(sparsity, SPARSE_BLOCK, SPARSE_BLOCK_DEFAULT),
        SPARSE_DIFFERENT_LAYOUT_PER_HEAD: get_scalar_param(
            sparsity, SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
            SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT),
    }
    if mode == SPARSE_DENSE_MODE:
        return common
    if mode == SPARSE_FIXED_MODE:
        extra_keys = [
            (SPARSE_NUM_LOCAL_BLOCKS, SPARSE_NUM_LOCAL_BLOCKS_DEFAULT),
            (SPARSE_NUM_GLOBAL_BLOCKS, SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT),
            (SPARSE_ATTENTION_TYPE, SPARSE_ATTENTION_TYPE_DEFAULT),
            (SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
             SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT),
            (SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS,
             SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT),
        ]
    elif mode == SPARSE_VARIABLE_MODE:
        extra_keys = [
            (SPARSE_NUM_RANDOM_BLOCKS, SPARSE_NUM_RANDOM_BLOCKS_DEFAULT),
            (SPARSE_LOCAL_WINDOW_BLOCKS, SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT),
            (SPARSE_GLOBAL_BLOCK_INDICES, SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT),
            (SPARSE_GLOBAL_BLOCK_END_INDICES,
             SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT),
            (SPARSE_ATTENTION_TYPE, SPARSE_ATTENTION_TYPE_DEFAULT),
            (SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
             SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT),
        ]
    elif mode == SPARSE_BIGBIRD_MODE:
        extra_keys = [
            (SPARSE_NUM_RANDOM_BLOCKS, SPARSE_NUM_RANDOM_BLOCKS_DEFAULT),
            (SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
             SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
            (SPARSE_NUM_GLOBAL_BLOCKS, SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT),
        ]
    elif mode == SPARSE_BSLONGFORMER_MODE:
        extra_keys = [
            (SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
             SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
            (SPARSE_GLOBAL_BLOCK_INDICES, SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT),
            (SPARSE_GLOBAL_BLOCK_END_INDICES,
             SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT),
        ]
    else:
        raise NotImplementedError(
            f"Given sparsity mode, {mode}, has not been implemented yet!")
    for key, default in extra_keys:
        common[key] = get_scalar_param(sparsity, key, default)
    return common


def get_pipeline_config(param_dict):
    """Pipeline section with defaults (reference: `runtime/config.py:348`)."""
    defaults = {
        PIPELINE_STAGES: PIPELINE_STAGES_DEFAULT,
        PIPELINE_PARTITION: PIPELINE_PARTITION_DEFAULT,
        PIPELINE_SEED_LAYERS: PIPELINE_SEED_LAYERS_DEFAULT,
        PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL:
            PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT,
    }
    config = dict(defaults)
    config.update(param_dict.get(PIPELINE, {}))
    return config


def get_mesh_config(param_dict):
    """TPU mesh axes: {"data": N|None, "model": M, "pipe": P, "seq": S, "expert": E}."""
    return param_dict.get(MESH, MESH_DEFAULT)


class CommQuantizationConfig:
    """Typed view of the ``comm_quantization`` block: the int8
    chunk-scaled gradient all-reduce (`runtime/comm/quantized.py`)."""

    def __init__(self, param_dict):
        sub = param_dict.get(COMM_QUANTIZATION, {}) or {}
        self.enabled = get_scalar_param(sub, COMM_QUANTIZATION_ENABLED,
                                        COMM_QUANTIZATION_ENABLED_DEFAULT)
        self.bits = get_scalar_param(sub, COMM_QUANTIZATION_BITS,
                                     COMM_QUANTIZATION_BITS_DEFAULT)
        self.chunk_size = get_scalar_param(
            sub, COMM_QUANTIZATION_CHUNK_SIZE,
            COMM_QUANTIZATION_CHUNK_SIZE_DEFAULT)
        self.bucket_mb = get_scalar_param(sub, COMM_QUANTIZATION_BUCKET_MB,
                                          COMM_QUANTIZATION_BUCKET_MB_DEFAULT)
        self.error_feedback = get_scalar_param(
            sub, COMM_QUANTIZATION_ERROR_FEEDBACK,
            COMM_QUANTIZATION_ERROR_FEEDBACK_DEFAULT)

    def __repr__(self):
        return (f"CommQuantizationConfig(enabled={self.enabled}, "
                f"bits={self.bits}, chunk_size={self.chunk_size}, "
                f"bucket_mb={self.bucket_mb}, "
                f"error_feedback={self.error_feedback})")


class ResilienceConfig:
    """Typed view of the ``resilience`` block: preemption-safe
    checkpointing + auto-resume + step health guards + fault injection
    (`runtime/resilience/`). See docs/resilience.md."""

    def __init__(self, param_dict):
        sub = param_dict.get(RESILIENCE, {}) or {}
        self.auto_resume = get_scalar_param(sub, RESILIENCE_AUTO_RESUME,
                                            RESILIENCE_AUTO_RESUME_DEFAULT)
        self.save_dir = get_scalar_param(sub, RESILIENCE_SAVE_DIR,
                                         RESILIENCE_SAVE_DIR_DEFAULT)
        self.save_interval_steps = get_scalar_param(
            sub, RESILIENCE_SAVE_INTERVAL_STEPS,
            RESILIENCE_SAVE_INTERVAL_STEPS_DEFAULT)

        ckpt = sub.get(RESILIENCE_CHECKPOINT, {}) or {}
        self.async_save = get_scalar_param(
            ckpt, RESILIENCE_CKPT_ASYNC_SAVE,
            RESILIENCE_CKPT_ASYNC_SAVE_DEFAULT)
        self.keep_last_n = get_scalar_param(
            ckpt, RESILIENCE_CKPT_KEEP_LAST_N,
            RESILIENCE_CKPT_KEEP_LAST_N_DEFAULT)
        self.io_retries = get_scalar_param(
            ckpt, RESILIENCE_CKPT_IO_RETRIES,
            RESILIENCE_CKPT_IO_RETRIES_DEFAULT)
        self.io_retry_base_s = get_scalar_param(
            ckpt, RESILIENCE_CKPT_IO_RETRY_BASE_S,
            RESILIENCE_CKPT_IO_RETRY_BASE_S_DEFAULT)
        self.io_timeout_s = get_scalar_param(
            ckpt, RESILIENCE_CKPT_IO_TIMEOUT_S,
            RESILIENCE_CKPT_IO_TIMEOUT_S_DEFAULT)

        guards = sub.get(RESILIENCE_GUARDS, {}) or {}
        nan = guards.get(RESILIENCE_GUARD_NAN, {}) or {}
        self.nan_guard_action = get_scalar_param(
            nan, RESILIENCE_GUARD_ACTION,
            RESILIENCE_GUARD_NAN_ACTION_DEFAULT)
        spike = guards.get(RESILIENCE_GUARD_LOSS_SPIKE, {}) or {}
        self.loss_spike_action = get_scalar_param(
            spike, RESILIENCE_GUARD_ACTION,
            RESILIENCE_GUARD_LOSS_SPIKE_ACTION_DEFAULT)
        self.loss_spike_window = get_scalar_param(
            spike, RESILIENCE_GUARD_LOSS_SPIKE_WINDOW,
            RESILIENCE_GUARD_LOSS_SPIKE_WINDOW_DEFAULT)
        self.loss_spike_factor = get_scalar_param(
            spike, RESILIENCE_GUARD_LOSS_SPIKE_FACTOR,
            RESILIENCE_GUARD_LOSS_SPIKE_FACTOR_DEFAULT)
        self.loss_spike_min_history = get_scalar_param(
            spike, RESILIENCE_GUARD_LOSS_SPIKE_MIN_HISTORY,
            RESILIENCE_GUARD_LOSS_SPIKE_MIN_HISTORY_DEFAULT)
        collapse = guards.get(RESILIENCE_GUARD_SCALE_COLLAPSE, {}) or {}
        self.scale_collapse_action = get_scalar_param(
            collapse, RESILIENCE_GUARD_ACTION,
            RESILIENCE_GUARD_SCALE_COLLAPSE_ACTION_DEFAULT)
        self.scale_collapse_patience = get_scalar_param(
            collapse, RESILIENCE_GUARD_SCALE_COLLAPSE_PATIENCE,
            RESILIENCE_GUARD_SCALE_COLLAPSE_PATIENCE_DEFAULT)

        preempt = sub.get(RESILIENCE_PREEMPTION, {}) or {}
        self.save_on_sigterm = get_scalar_param(
            preempt, RESILIENCE_PREEMPTION_SAVE_ON_SIGTERM,
            RESILIENCE_PREEMPTION_SAVE_ON_SIGTERM_DEFAULT)

        fi = sub.get(RESILIENCE_FAULT_INJECTION, {}) or {}
        self.fault_injection = get_scalar_param(
            fi, RESILIENCE_FAULT_INJECTION_ENABLED,
            RESILIENCE_FAULT_INJECTION_ENABLED_DEFAULT)

        hot = sub.get(RESILIENCE_HOT_CHECKPOINT, {}) or {}
        self.hot_enabled = get_scalar_param(
            hot, RESILIENCE_HOT_ENABLED, RESILIENCE_HOT_ENABLED_DEFAULT)
        self.hot_interval_steps = get_scalar_param(
            hot, RESILIENCE_HOT_INTERVAL_STEPS,
            RESILIENCE_HOT_INTERVAL_STEPS_DEFAULT)
        self.hot_capacity = get_scalar_param(
            hot, RESILIENCE_HOT_CAPACITY, RESILIENCE_HOT_CAPACITY_DEFAULT)
        self.hot_mirror_dir = get_scalar_param(
            hot, RESILIENCE_HOT_MIRROR_DIR,
            RESILIENCE_HOT_MIRROR_DIR_DEFAULT)
        self.hot_mirror_keep = get_scalar_param(
            hot, RESILIENCE_HOT_MIRROR_KEEP,
            RESILIENCE_HOT_MIRROR_KEEP_DEFAULT)

        self.host_adam_retries = get_scalar_param(
            sub, RESILIENCE_HOST_ADAM_RETRIES,
            RESILIENCE_HOST_ADAM_RETRIES_DEFAULT)

    @property
    def guards_enabled(self):
        return any(a is not None for a in (self.nan_guard_action,
                                           self.loss_spike_action,
                                           self.scale_collapse_action))

    @property
    def enabled(self):
        return bool(self.auto_resume or self.save_interval_steps or
                    self.guards_enabled or self.save_on_sigterm or
                    self.fault_injection or self.save_dir)

    def __repr__(self):
        return (f"ResilienceConfig(auto_resume={self.auto_resume}, "
                f"save_dir={self.save_dir!r}, "
                f"save_interval_steps={self.save_interval_steps}, "
                f"async_save={self.async_save}, "
                f"keep_last_n={self.keep_last_n}, "
                f"guards=[nan={self.nan_guard_action}, "
                f"loss_spike={self.loss_spike_action}, "
                f"scale_collapse={self.scale_collapse_action}], "
                f"save_on_sigterm={self.save_on_sigterm}, "
                f"fault_injection={self.fault_injection})")


class ElasticityConfig:
    """Typed view of the ``elasticity`` block: topology-agnostic
    checkpoint resume + elastic batch solving (`runtime/elastic/`).
    See docs/elasticity.md."""

    def __init__(self, param_dict):
        sub = param_dict.get(ELASTICITY, {}) or {}
        self.enabled = get_scalar_param(sub, ELASTICITY_ENABLED,
                                        ELASTICITY_ENABLED_DEFAULT)
        self.target_global_batch = get_scalar_param(
            sub, ELASTICITY_TARGET_GLOBAL_BATCH,
            ELASTICITY_TARGET_GLOBAL_BATCH_DEFAULT)
        self.max_world_size = get_scalar_param(
            sub, ELASTICITY_MAX_WORLD_SIZE,
            ELASTICITY_MAX_WORLD_SIZE_DEFAULT)
        self.strict = get_scalar_param(sub, ELASTICITY_STRICT,
                                       ELASTICITY_STRICT_DEFAULT)
        self.lr_scaling = get_scalar_param(sub, ELASTICITY_LR_SCALING,
                                           ELASTICITY_LR_SCALING_DEFAULT)

    def __repr__(self):
        return (f"ElasticityConfig(enabled={self.enabled}, "
                f"target_global_batch={self.target_global_batch}, "
                f"max_world_size={self.max_world_size}, "
                f"strict={self.strict}, lr_scaling={self.lr_scaling!r})")


class AnalysisConfig:
    """Typed view of the ``analysis`` block: opt-in compile-time audits
    of the compiled train step (`deepspeed_tpu/analysis/`) — donation/
    aliasing, ZeRO byte budgets, dtype hygiene, host transfers, loop
    trip counts, plus the per-step recompile detector.
    See docs/analysis.md."""

    def __init__(self, param_dict):
        sub = param_dict.get(ANALYSIS, {}) or {}
        self.enabled = get_scalar_param(sub, ANALYSIS_ENABLED,
                                        ANALYSIS_ENABLED_DEFAULT)
        self.fail_on_findings = get_scalar_param(
            sub, ANALYSIS_FAIL_ON_FINDINGS,
            ANALYSIS_FAIL_ON_FINDINGS_DEFAULT)
        self.rules = get_scalar_param(sub, ANALYSIS_RULES,
                                      ANALYSIS_RULES_DEFAULT)
        self.check_recompile = get_scalar_param(
            sub, ANALYSIS_CHECK_RECOMPILE,
            ANALYSIS_CHECK_RECOMPILE_DEFAULT)
        self.peak_memory_budget_mb = get_scalar_param(
            sub, ANALYSIS_PEAK_MEMORY_BUDGET_MB,
            ANALYSIS_PEAK_MEMORY_BUDGET_MB_DEFAULT)
        self.platform = get_scalar_param(sub, ANALYSIS_PLATFORM,
                                         ANALYSIS_PLATFORM_DEFAULT)

    def __repr__(self):
        return (f"AnalysisConfig(enabled={self.enabled}, "
                f"fail_on_findings={self.fail_on_findings}, "
                f"rules={self.rules!r}, "
                f"check_recompile={self.check_recompile}, "
                f"peak_memory_budget_mb={self.peak_memory_budget_mb}, "
                f"platform={self.platform!r})")


class TelemetryConfig:
    """Typed view of the ``telemetry`` block: the unified runtime
    telemetry session (`deepspeed_tpu/telemetry/`) — metrics registry,
    step-phase spans, schema-versioned JSONL event log, and the
    JSONL/console/Prometheus-textfile exporters the ``ds_tpu_metrics``
    CLI and scrapers read. See docs/observability.md."""

    KEYS = (TELEMETRY_ENABLED, TELEMETRY_JSONL_PATH, TELEMETRY_CONSOLE,
            TELEMETRY_PROMETHEUS_TEXTFILE, TELEMETRY_PROMETHEUS_WRITE_EVERY,
            TELEMETRY_HISTORY, TELEMETRY_STAMP_STATIC_FACTS,
            TELEMETRY_FLOPS_PER_TOKEN, TELEMETRY_CRASH_DUMP_DIR,
            TELEMETRY_FLIGHT_HISTORY, TELEMETRY_WATCHDOG,
            TELEMETRY_ANOMALY_TRACE)
    WATCHDOG_KEYS = (TELEMETRY_WATCHDOG_ENABLED,
                     TELEMETRY_WATCHDOG_DEADLINE_FACTOR,
                     TELEMETRY_WATCHDOG_MIN_DEADLINE_S,
                     TELEMETRY_WATCHDOG_ACTION)
    ANOMALY_KEYS = (TELEMETRY_ANOMALY_TRACE_ENABLED,
                    TELEMETRY_ANOMALY_TRACE_FACTOR,
                    TELEMETRY_ANOMALY_TRACE_WINDOW,
                    TELEMETRY_ANOMALY_TRACE_CAPTURE_STEPS)

    def __init__(self, param_dict):
        sub = param_dict.get(TELEMETRY, {}) or {}
        self._given_keys = tuple(sub)
        self.enabled = get_scalar_param(sub, TELEMETRY_ENABLED,
                                        TELEMETRY_ENABLED_DEFAULT)
        self.jsonl_path = get_scalar_param(sub, TELEMETRY_JSONL_PATH,
                                           TELEMETRY_JSONL_PATH_DEFAULT)
        self.console = get_scalar_param(sub, TELEMETRY_CONSOLE,
                                        TELEMETRY_CONSOLE_DEFAULT)
        self.prometheus_textfile = get_scalar_param(
            sub, TELEMETRY_PROMETHEUS_TEXTFILE,
            TELEMETRY_PROMETHEUS_TEXTFILE_DEFAULT)
        self.prometheus_write_every = get_scalar_param(
            sub, TELEMETRY_PROMETHEUS_WRITE_EVERY,
            TELEMETRY_PROMETHEUS_WRITE_EVERY_DEFAULT)
        self.history = get_scalar_param(sub, TELEMETRY_HISTORY,
                                        TELEMETRY_HISTORY_DEFAULT)
        self.stamp_static_facts = get_scalar_param(
            sub, TELEMETRY_STAMP_STATIC_FACTS,
            TELEMETRY_STAMP_STATIC_FACTS_DEFAULT)
        self.flops_per_token = get_scalar_param(
            sub, TELEMETRY_FLOPS_PER_TOKEN,
            TELEMETRY_FLOPS_PER_TOKEN_DEFAULT)
        self.crash_dump_dir = get_scalar_param(
            sub, TELEMETRY_CRASH_DUMP_DIR, TELEMETRY_CRASH_DUMP_DIR_DEFAULT)
        self.flight_history = get_scalar_param(
            sub, TELEMETRY_FLIGHT_HISTORY, TELEMETRY_FLIGHT_HISTORY_DEFAULT)
        wd = sub.get(TELEMETRY_WATCHDOG, {}) or {}
        self._watchdog_given_keys = tuple(wd)
        self.watchdog_enabled = get_scalar_param(
            wd, TELEMETRY_WATCHDOG_ENABLED,
            TELEMETRY_WATCHDOG_ENABLED_DEFAULT)
        self.watchdog_deadline_factor = get_scalar_param(
            wd, TELEMETRY_WATCHDOG_DEADLINE_FACTOR,
            TELEMETRY_WATCHDOG_DEADLINE_FACTOR_DEFAULT)
        self.watchdog_min_deadline_s = get_scalar_param(
            wd, TELEMETRY_WATCHDOG_MIN_DEADLINE_S,
            TELEMETRY_WATCHDOG_MIN_DEADLINE_S_DEFAULT)
        self.watchdog_action = get_scalar_param(
            wd, TELEMETRY_WATCHDOG_ACTION, TELEMETRY_WATCHDOG_ACTION_DEFAULT)
        an = sub.get(TELEMETRY_ANOMALY_TRACE, {}) or {}
        self._anomaly_given_keys = tuple(an)
        self.anomaly_trace_enabled = get_scalar_param(
            an, TELEMETRY_ANOMALY_TRACE_ENABLED,
            TELEMETRY_ANOMALY_TRACE_ENABLED_DEFAULT)
        self.anomaly_trace_factor = get_scalar_param(
            an, TELEMETRY_ANOMALY_TRACE_FACTOR,
            TELEMETRY_ANOMALY_TRACE_FACTOR_DEFAULT)
        self.anomaly_trace_window = get_scalar_param(
            an, TELEMETRY_ANOMALY_TRACE_WINDOW,
            TELEMETRY_ANOMALY_TRACE_WINDOW_DEFAULT)
        self.anomaly_trace_capture_steps = get_scalar_param(
            an, TELEMETRY_ANOMALY_TRACE_CAPTURE_STEPS,
            TELEMETRY_ANOMALY_TRACE_CAPTURE_STEPS_DEFAULT)

    def __repr__(self):
        return (f"TelemetryConfig(enabled={self.enabled}, "
                f"jsonl_path={self.jsonl_path!r}, "
                f"console={self.console}, "
                f"prometheus_textfile={self.prometheus_textfile!r}, "
                f"history={self.history}, "
                f"stamp_static_facts={self.stamp_static_facts}, "
                f"flops_per_token={self.flops_per_token}, "
                f"crash_dump_dir={self.crash_dump_dir!r}, "
                f"watchdog_enabled={self.watchdog_enabled}, "
                f"anomaly_trace_enabled={self.anomaly_trace_enabled})")


class TensorParallelConfig:
    """Typed view of the ``tensor_parallel`` block. Its ``overlap``
    sub-block opts the manual-mode TP/SP/MoE layers into the
    latency-hiding collective matmul (chunked ppermute rings pipelined
    against the adjacent matmuls, ``parallel/collectives.py``).
    See docs/tensor-parallel.md."""

    def __init__(self, param_dict):
        sub = param_dict.get(TENSOR_PARALLEL, {}) or {}
        ov = sub.get(TP_OVERLAP, {}) or {}
        self.overlap_enabled = get_scalar_param(ov, TP_OVERLAP_ENABLED,
                                                TP_OVERLAP_ENABLED_DEFAULT)
        self.overlap_chunks = get_scalar_param(ov, TP_OVERLAP_CHUNKS,
                                               TP_OVERLAP_CHUNKS_DEFAULT)
        self.overlap_bidirectional = get_scalar_param(
            ov, TP_OVERLAP_BIDIRECTIONAL, TP_OVERLAP_BIDIRECTIONAL_DEFAULT)
        self.overlap_sites = get_scalar_param(ov, TP_OVERLAP_SITES,
                                              TP_OVERLAP_SITES_DEFAULT)
        self.overlap_wire_dtype = get_scalar_param(
            ov, TP_OVERLAP_WIRE_DTYPE, TP_OVERLAP_WIRE_DTYPE_DEFAULT)
        self.overlap_wire_chunk = get_scalar_param(
            ov, TP_OVERLAP_WIRE_CHUNK, TP_OVERLAP_WIRE_CHUNK_DEFAULT)

    def overlap_plan(self):
        """The resolved :class:`~..parallel.collectives.OverlapPlan`, or
        None when overlap is disabled (layers keep their monolithic
        collectives)."""
        if not self.overlap_enabled:
            return None
        from deepspeed_tpu.parallel.collectives import OverlapPlan
        wd = self.overlap_wire_dtype
        return OverlapPlan(chunks=int(self.overlap_chunks),
                           bidirectional=bool(self.overlap_bidirectional),
                           sites=dict(self.overlap_sites or {}),
                           wire_dtype=(str(wd) if wd else None),
                           wire_chunk=int(self.overlap_wire_chunk))

    def __repr__(self):
        return (f"TensorParallelConfig(overlap_enabled="
                f"{self.overlap_enabled}, "
                f"overlap_chunks={self.overlap_chunks}, "
                f"overlap_bidirectional={self.overlap_bidirectional}, "
                f"overlap_sites={self.overlap_sites!r}, "
                f"overlap_wire_dtype={self.overlap_wire_dtype!r}, "
                f"overlap_wire_chunk={self.overlap_wire_chunk})")


class Fp8Config:
    """Typed view of the ``fp8`` block (ops/fp8.py; docs/fp8.md).

    ``enabled`` switches the model's hooked matmuls to delayed-scaling
    fp8 GEMMs (``f8e4m3fn`` forward operands / ``f8e5m2`` backward
    cotangents, per-tensor amax histories carried as engine state);
    ``margin`` / ``amax_history_len`` tune the scaling recipe and
    ``sites`` holds per-site ``{"enabled": bool}`` overrides. The
    ``wire`` sub-block quantizes the overlapped collective rings'
    payloads through the shared codec registry
    (``runtime/comm/codecs.py``), including ZeRO-3 gathers."""

    def __init__(self, param_dict):
        sub = param_dict.get(FP8, {}) or {}
        self.enabled = get_scalar_param(sub, FP8_ENABLED,
                                        FP8_ENABLED_DEFAULT)
        self.margin = get_scalar_param(sub, FP8_MARGIN, FP8_MARGIN_DEFAULT)
        self.amax_history_len = get_scalar_param(
            sub, FP8_AMAX_HISTORY_LEN, FP8_AMAX_HISTORY_LEN_DEFAULT)
        self.sites = get_scalar_param(sub, FP8_SITES, FP8_SITES_DEFAULT)
        wire = sub.get(FP8_WIRE, {}) or {}
        self.wire_enabled = get_scalar_param(wire, FP8_WIRE_ENABLED,
                                             FP8_WIRE_ENABLED_DEFAULT)
        self.wire_dtype = get_scalar_param(wire, FP8_WIRE_DTYPE,
                                           FP8_WIRE_DTYPE_DEFAULT)
        self.wire_chunk_size = get_scalar_param(
            wire, FP8_WIRE_CHUNK_SIZE, FP8_WIRE_CHUNK_SIZE_DEFAULT)

    def plan(self):
        """The resolved :class:`~..ops.fp8.Fp8Plan`, or None when fp8
        matmuls are disabled."""
        if not self.enabled:
            return None
        from deepspeed_tpu.ops.fp8 import Fp8Plan
        return Fp8Plan(margin=int(self.margin),
                       amax_history_len=int(self.amax_history_len),
                       sites=dict(self.sites or {}))

    def active_wire_dtype(self):
        """The codec name for quantized collective wires, or None."""
        return str(self.wire_dtype) if self.wire_enabled else None

    def __repr__(self):
        return (f"Fp8Config(enabled={self.enabled}, "
                f"margin={self.margin}, "
                f"amax_history_len={self.amax_history_len}, "
                f"sites={self.sites!r}, "
                f"wire_enabled={self.wire_enabled}, "
                f"wire_dtype={self.wire_dtype!r}, "
                f"wire_chunk_size={self.wire_chunk_size})")


class InferenceConfig:
    """Typed view of the ``inference`` block: the jitted autoregressive
    serving engine (`deepspeed_tpu/inference/`; docs/inference.md).

    ``max_batch`` sizes the KV cache's row ring (= the compiled decode
    batch); ``seq_buckets`` are host-side per-request length budgets
    (the cache buffer is sized to their max — buckets are NOT compiled
    shapes, so any bucket mix costs exactly one prefill + one decode
    compile); ``prefill_chunk`` fixes the chunked-prefill shape;
    ``kv_cache_dtype`` selects plain (``bf16``/``f32``) or codec
    -quantized (``int8``/``f8e4m3fn``/``f8e5m2``) cache storage."""

    KEYS = (INFERENCE_MAX_BATCH, INFERENCE_SEQ_BUCKETS,
            INFERENCE_PREFILL_CHUNK, INFERENCE_KV_CACHE_DTYPE,
            INFERENCE_MAX_NEW_TOKENS, INFERENCE_ATTENTION_IMPL,
            INFERENCE_ATTENTION_BLOCK_K, INFERENCE_TEMPERATURE,
            INFERENCE_TOP_K, INFERENCE_TOP_P, INFERENCE_SAMPLING_SEED,
            INFERENCE_KV_LAYOUT, INFERENCE_PAGE_SIZE, INFERENCE_N_PAGES,
            INFERENCE_PREFIX_CACHE, INFERENCE_HOST_PARK_THRESHOLD,
            INFERENCE_REPLICAS, INFERENCE_MAX_REDISPATCH,
            INFERENCE_MAX_QUEUE_DEPTH, INFERENCE_DEADLINE_S,
            INFERENCE_QUEUE_TIMEOUT_S, INFERENCE_SPECULATIVE,
            INFERENCE_DISAGGREGATED, INFERENCE_PREFILL_WORKERS,
            INFERENCE_DECODE_WORKERS, INFERENCE_PREFILL_MAX_BATCH,
            INFERENCE_DECODE_MAX_BATCH)

    SPECULATIVE_KEYS = (INFERENCE_SPECULATIVE_ENABLED,
                        INFERENCE_SPECULATIVE_K,
                        INFERENCE_SPECULATIVE_DRAFT_LAYERS,
                        INFERENCE_SPECULATIVE_MIN_ACCEPT_TO_GROW)

    def __init__(self, param_dict):
        sub = param_dict.get(INFERENCE, {}) or {}
        self._given_keys = tuple(sub)
        self.max_batch = get_scalar_param(sub, INFERENCE_MAX_BATCH,
                                          INFERENCE_MAX_BATCH_DEFAULT)
        buckets = get_scalar_param(sub, INFERENCE_SEQ_BUCKETS,
                                   INFERENCE_SEQ_BUCKETS_DEFAULT)
        self.seq_buckets = tuple(buckets) if buckets is not None else ()
        self.prefill_chunk = get_scalar_param(
            sub, INFERENCE_PREFILL_CHUNK, INFERENCE_PREFILL_CHUNK_DEFAULT)
        self.kv_cache_dtype = get_scalar_param(
            sub, INFERENCE_KV_CACHE_DTYPE, INFERENCE_KV_CACHE_DTYPE_DEFAULT)
        self.max_new_tokens = get_scalar_param(
            sub, INFERENCE_MAX_NEW_TOKENS, INFERENCE_MAX_NEW_TOKENS_DEFAULT)
        self.attention_impl = get_scalar_param(
            sub, INFERENCE_ATTENTION_IMPL, INFERENCE_ATTENTION_IMPL_DEFAULT)
        self.attention_block_k = get_scalar_param(
            sub, INFERENCE_ATTENTION_BLOCK_K,
            INFERENCE_ATTENTION_BLOCK_K_DEFAULT)
        self.temperature = get_scalar_param(
            sub, INFERENCE_TEMPERATURE, INFERENCE_TEMPERATURE_DEFAULT)
        self.top_k = get_scalar_param(sub, INFERENCE_TOP_K,
                                      INFERENCE_TOP_K_DEFAULT)
        self.top_p = get_scalar_param(sub, INFERENCE_TOP_P,
                                      INFERENCE_TOP_P_DEFAULT)
        self.sampling_seed = get_scalar_param(
            sub, INFERENCE_SAMPLING_SEED, INFERENCE_SAMPLING_SEED_DEFAULT)
        self.kv_layout = get_scalar_param(
            sub, INFERENCE_KV_LAYOUT, INFERENCE_KV_LAYOUT_DEFAULT)
        self.page_size = get_scalar_param(
            sub, INFERENCE_PAGE_SIZE, INFERENCE_PAGE_SIZE_DEFAULT)
        self.n_pages = get_scalar_param(
            sub, INFERENCE_N_PAGES, INFERENCE_N_PAGES_DEFAULT)
        self.prefix_cache = get_scalar_param(
            sub, INFERENCE_PREFIX_CACHE, INFERENCE_PREFIX_CACHE_DEFAULT)
        self.host_park_threshold = get_scalar_param(
            sub, INFERENCE_HOST_PARK_THRESHOLD,
            INFERENCE_HOST_PARK_THRESHOLD_DEFAULT)
        self.replicas = get_scalar_param(
            sub, INFERENCE_REPLICAS, INFERENCE_REPLICAS_DEFAULT)
        self.max_redispatch = get_scalar_param(
            sub, INFERENCE_MAX_REDISPATCH, INFERENCE_MAX_REDISPATCH_DEFAULT)
        self.max_queue_depth = get_scalar_param(
            sub, INFERENCE_MAX_QUEUE_DEPTH,
            INFERENCE_MAX_QUEUE_DEPTH_DEFAULT)
        self.deadline_s = get_scalar_param(
            sub, INFERENCE_DEADLINE_S, INFERENCE_DEADLINE_S_DEFAULT)
        self.queue_timeout_s = get_scalar_param(
            sub, INFERENCE_QUEUE_TIMEOUT_S, INFERENCE_QUEUE_TIMEOUT_S_DEFAULT)
        self.disaggregated = get_scalar_param(
            sub, INFERENCE_DISAGGREGATED, INFERENCE_DISAGGREGATED_DEFAULT)
        self.prefill_workers = get_scalar_param(
            sub, INFERENCE_PREFILL_WORKERS,
            INFERENCE_PREFILL_WORKERS_DEFAULT)
        self.decode_workers = get_scalar_param(
            sub, INFERENCE_DECODE_WORKERS,
            INFERENCE_DECODE_WORKERS_DEFAULT)
        self.prefill_max_batch = get_scalar_param(
            sub, INFERENCE_PREFILL_MAX_BATCH,
            INFERENCE_PREFILL_MAX_BATCH_DEFAULT)
        self.decode_max_batch = get_scalar_param(
            sub, INFERENCE_DECODE_MAX_BATCH,
            INFERENCE_DECODE_MAX_BATCH_DEFAULT)
        spec = sub.get(INFERENCE_SPECULATIVE, {}) or {}
        self._speculative_raw = spec
        self._speculative_given_keys = tuple(spec) \
            if isinstance(spec, dict) else ()
        self.speculative_enabled = get_scalar_param(
            spec, INFERENCE_SPECULATIVE_ENABLED,
            INFERENCE_SPECULATIVE_ENABLED_DEFAULT) \
            if isinstance(spec, dict) else None
        self.speculative_k = get_scalar_param(
            spec, INFERENCE_SPECULATIVE_K,
            INFERENCE_SPECULATIVE_K_DEFAULT) \
            if isinstance(spec, dict) else None
        self.speculative_draft_layers = get_scalar_param(
            spec, INFERENCE_SPECULATIVE_DRAFT_LAYERS,
            INFERENCE_SPECULATIVE_DRAFT_LAYERS_DEFAULT) \
            if isinstance(spec, dict) else None
        self.speculative_min_accept_to_grow = get_scalar_param(
            spec, INFERENCE_SPECULATIVE_MIN_ACCEPT_TO_GROW,
            INFERENCE_SPECULATIVE_MIN_ACCEPT_TO_GROW_DEFAULT) \
            if isinstance(spec, dict) else None

    @property
    def speculative(self):
        """The validated block in the dict form the engine's
        ``build_speculative`` consumes (None when disabled)."""
        if not self.speculative_enabled:
            return None
        return {
            INFERENCE_SPECULATIVE_ENABLED: True,
            INFERENCE_SPECULATIVE_K: self.speculative_k,
            INFERENCE_SPECULATIVE_DRAFT_LAYERS:
                self.speculative_draft_layers,
            INFERENCE_SPECULATIVE_MIN_ACCEPT_TO_GROW:
                self.speculative_min_accept_to_grow,
        }

    def __repr__(self):
        return (f"InferenceConfig(max_batch={self.max_batch}, "
                f"seq_buckets={self.seq_buckets}, "
                f"prefill_chunk={self.prefill_chunk}, "
                f"kv_cache_dtype={self.kv_cache_dtype!r}, "
                f"max_new_tokens={self.max_new_tokens}, "
                f"attention_impl={self.attention_impl!r}, "
                f"attention_block_k={self.attention_block_k}, "
                f"temperature={self.temperature}, top_k={self.top_k}, "
                f"top_p={self.top_p}, "
                f"sampling_seed={self.sampling_seed}, "
                f"kv_layout={self.kv_layout!r}, "
                f"page_size={self.page_size}, n_pages={self.n_pages}, "
                f"prefix_cache={self.prefix_cache}, "
                f"host_park_threshold={self.host_park_threshold}, "
                f"replicas={self.replicas}, "
                f"max_redispatch={self.max_redispatch}, "
                f"max_queue_depth={self.max_queue_depth}, "
                f"deadline_s={self.deadline_s}, "
                f"queue_timeout_s={self.queue_timeout_s}, "
                f"disaggregated={self.disaggregated}, "
                f"prefill_workers={self.prefill_workers}, "
                f"decode_workers={self.decode_workers}, "
                f"prefill_max_batch={self.prefill_max_batch}, "
                f"decode_max_batch={self.decode_max_batch})")


class DeepSpeedConfig:
    def __init__(self, json_file_or_dict, mpu=None, param_dict=None, world_size=None):
        if param_dict is None:
            if isinstance(json_file_or_dict, dict):
                param_dict = json_file_or_dict
            else:
                with open(json_file_or_dict, "r") as f:
                    param_dict = json.load(
                        f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        self._param_dict = param_dict

        if world_size is not None:
            self.world_size = world_size
        elif mpu is not None:
            self.world_size = mpu.get_data_parallel_world_size()
        else:
            self.world_size = self._infer_world_size(param_dict)

        self._initialize_params(param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    def _infer_world_size(self, param_dict):
        """Data-parallel world size = total devices / (model*pipe*seq*expert)."""
        try:
            import jax
            n_devices = jax.device_count()
        except Exception:
            n_devices = 1
        mesh = get_mesh_config(param_dict)
        if mesh:
            denom = 1
            for axis, size in mesh.items():
                if axis != "data" and size:
                    denom *= size
            data = mesh.get("data")
            if data:
                return data
            return max(n_devices // denom, 1)
        return n_devices

    def _initialize_params(self, param_dict):
        self.train_batch_size = get_scalar_param(param_dict, TRAIN_BATCH_SIZE,
                                                 TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = get_scalar_param(
            param_dict, TRAIN_MICRO_BATCH_SIZE_PER_GPU,
            TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = get_gradient_accumulation_steps(param_dict)
        self.steps_per_print = get_scalar_param(param_dict, STEPS_PER_PRINT,
                                                STEPS_PER_PRINT_DEFAULT)
        self.dump_state = get_scalar_param(param_dict, DUMP_STATE, DUMP_STATE_DEFAULT)
        self.disable_allgather = get_scalar_param(param_dict, DISABLE_ALLGATHER,
                                                  DISABLE_ALLGATHER_DEFAULT)
        self.gradient_predivide_factor = get_scalar_param(
            param_dict, GRADIENT_PREDIVIDE_FACTOR, GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = get_sparse_gradients_enabled(param_dict)

        self.zero_config = DeepSpeedZeroConfig(param_dict)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.activation_checkpointing_config = \
            DeepSpeedActivationCheckpointingConfig(param_dict)

        self.fp16_enabled = get_fp16_enabled(param_dict)
        self.bf16_enabled = get_bf16_enabled(param_dict)
        self.amp_enabled = get_amp_enabled(param_dict)
        self.amp_params = get_amp_params(param_dict)
        self.loss_scale = get_loss_scale(param_dict)
        self.initial_dynamic_scale = get_initial_dynamic_scale(param_dict)
        self.dynamic_loss_scale_args = get_dynamic_loss_scale_args(param_dict)

        self.optimizer_name = get_optimizer_name(param_dict)
        if self.optimizer_name is not None and \
                self.optimizer_name.lower() in DEEPSPEED_OPTIMIZERS:
            self.optimizer_name = self.optimizer_name.lower()
        self.optimizer_params = get_optimizer_params(param_dict)
        self.optimizer_legacy_fusion = get_optimizer_legacy_fusion(param_dict)
        self.zero_allow_untested_optimizer = get_scalar_param(
            param_dict, ZERO_ALLOW_UNTESTED_OPTIMIZER,
            ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)

        self.scheduler_name = get_scheduler_name(param_dict)
        self.scheduler_params = get_scheduler_params(param_dict)

        self.wall_clock_breakdown = get_scalar_param(
            param_dict, WALL_CLOCK_BREAKDOWN, WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = get_scalar_param(param_dict, MEMORY_BREAKDOWN,
                                                 MEMORY_BREAKDOWN_DEFAULT)
        # device-time profiling window (jax.profiler trace; SURVEY §5.1's
        # xprof equivalent) — {"trace_dir", "trace_start_step",
        # "trace_num_steps"}
        self.profiling_params = param_dict.get("profiling", None)
        # persistent XLA compilation cache (first 350M-step compile is
        # ~2 min on a v5e; a shared cache dir makes restarts/pod workers
        # hit it instead)
        self.compilation_cache_dir = get_scalar_param(
            param_dict, "compilation_cache_dir", None)
        if TENSORBOARD in param_dict:
            tb = param_dict[TENSORBOARD]
            self.tensorboard_enabled = get_scalar_param(tb, TENSORBOARD_ENABLED,
                                                        TENSORBOARD_ENABLED_DEFAULT)
            self.tensorboard_output_path = get_scalar_param(
                tb, TENSORBOARD_OUTPUT_PATH, TENSORBOARD_OUTPUT_PATH_DEFAULT)
            self.tensorboard_job_name = get_scalar_param(
                tb, TENSORBOARD_JOB_NAME, TENSORBOARD_JOB_NAME_DEFAULT)
        else:
            self.tensorboard_enabled = TENSORBOARD_ENABLED_DEFAULT
            self.tensorboard_output_path = TENSORBOARD_OUTPUT_PATH_DEFAULT
            self.tensorboard_job_name = TENSORBOARD_JOB_NAME_DEFAULT

        self.gradient_clipping = get_scalar_param(param_dict, GRADIENT_CLIPPING,
                                                  GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = get_scalar_param(param_dict, PRESCALE_GRADIENTS,
                                                   PRESCALE_GRADIENTS_DEFAULT)
        self.fp32_allreduce = get_scalar_param(param_dict, FP32_ALLREDUCE,
                                               FP32_ALLREDUCE_DEFAULT)
        self.vocabulary_size = get_scalar_param(param_dict, VOCABULARY_SIZE,
                                                VOCABULARY_SIZE_DEFAULT)

        self.pld_enabled = get_pld_enabled(param_dict)
        self.pld_params = get_pld_params(param_dict)

        self.sparse_attention = get_sparse_attention(param_dict)
        self.pipeline = get_pipeline_config(param_dict)
        self.mesh_shape = get_mesh_config(param_dict)
        self.comm_quantization = CommQuantizationConfig(param_dict)
        self.resilience = ResilienceConfig(param_dict)
        self.elasticity = ElasticityConfig(param_dict)
        self.analysis = AnalysisConfig(param_dict)
        self.telemetry = TelemetryConfig(param_dict)
        self.tensor_parallel = TensorParallelConfig(param_dict)
        self.fp8 = Fp8Config(param_dict)
        self.inference = InferenceConfig(param_dict)
        # Set by the elastic batch solver when the target batch cannot
        # factor exactly at this world size; the engine multiplies it
        # into the lr schedule.
        self.elastic_lr_scale = 1.0

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        assert train_batch > 0, \
            f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, \
            f"Micro batch size per gpu: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, \
            f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            f"Check batch related parameters. train_batch_size is not equal "
            f"to micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {grad_acc} * {self.world_size}")

    def _set_batch_related_parameters(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        # All three provided → consistency-checked below.
        if train_batch is not None and micro_batch is not None and grad_acc is not None:
            pass
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= self.world_size
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // self.world_size
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * self.world_size
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // self.world_size
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * self.world_size
            self.gradient_accumulation_steps = 1
        else:
            raise ValueError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "needs to be provided")

    def _configure_train_batch_size(self):
        if self.elasticity.enabled:
            self._solve_elastic_batch()
        self._set_batch_related_parameters()
        self._batch_assertion()

    def _solve_elastic_batch(self):
        """Re-derive micro x grad_accum for the current world size.

        With elasticity on, the *target global batch* (the elasticity
        block's, or train_batch_size) is the invariant — a pinned
        micro/accum pair from a different world size is a preference,
        not a constraint, so a resumed run at a new world keeps the
        effective batch (and LR schedule cadence) instead of failing the
        triple assertion or silently training at a different batch.
        """
        from deepspeed_tpu.runtime.elastic.batch import solve_elastic_batch
        el = self.elasticity
        target = el.target_global_batch or self.train_batch_size
        if target is None and self.train_micro_batch_size_per_gpu:
            # No global target anywhere: the user thinks per-device;
            # nothing for the solver to preserve.
            return
        if target is None:
            raise ValueError(
                "elasticity: set target_global_batch (or train_batch_size)"
                " — the solver needs a global batch to preserve")
        plan = solve_elastic_batch(
            target, self.world_size,
            prefer_micro=self.train_micro_batch_size_per_gpu,
            prefer_accum=self.gradient_accumulation_steps,
            lr_scaling=el.lr_scaling, strict=el.strict)
        if not plan.exact:
            logger.warning(
                "elasticity: target_global_batch %s does not divide by "
                "world size %s; training at %s with lr scaled by %.6g "
                "(%s rule)", target, self.world_size, plan.global_batch,
                plan.lr_scale, el.lr_scaling)
        if self.train_micro_batch_size_per_gpu is not None and \
                plan.micro_batch != self.train_micro_batch_size_per_gpu:
            logger.info(
                "elasticity: re-factored batch for world size %s: "
                "micro %s -> %s, accum %s -> %s", self.world_size,
                self.train_micro_batch_size_per_gpu, plan.micro_batch,
                self.gradient_accumulation_steps, plan.grad_accum)
        self.train_batch_size = plan.global_batch
        self.train_micro_batch_size_per_gpu = plan.micro_batch
        self.gradient_accumulation_steps = plan.grad_accum
        self.elastic_lr_scale = plan.lr_scale

    def _do_sanity_check(self):
        self._do_error_check()
        self._do_warning_check()

    def _do_error_check(self):
        if self.zero_enabled:
            assert self.fp16_enabled or self.bf16_enabled, (
                "DeepSpeedConfig: ZeRO is only supported with fp16 or bf16 enabled")
            assert self.zero_optimization_stage <= MAX_STAGE_ZERO_OPTIMIZATION, (
                f"DeepSpeedConfig: Maximum supported ZeRO stage is "
                f"{MAX_STAGE_ZERO_OPTIMIZATION}")
        assert self.train_micro_batch_size_per_gpu is not None, \
            "DeepSpeedConfig: train_micro_batch_size_per_gpu is not defined"
        assert self.gradient_accumulation_steps is not None, \
            "DeepSpeedConfig: gradient_accumulation_steps is not defined"
        if self.fp16_enabled and self.bf16_enabled:
            raise ValueError("fp16 and bf16 cannot both be enabled")
        if self.comm_quantization.enabled:
            cq = self.comm_quantization
            assert cq.bits == 8, (
                f"comm_quantization: only 8-bit quantization is "
                f"implemented, got bits={cq.bits}")
            assert cq.chunk_size > 0 and cq.chunk_size % 2 == 0, (
                f"comm_quantization: chunk_size must be a positive even "
                f"int, got {cq.chunk_size}")
            assert cq.bucket_mb > 0, (
                f"comm_quantization: bucket_mb must be positive, "
                f"got {cq.bucket_mb}")
            assert self.zero_optimization_stage <= 2, (
                "comm_quantization covers the dense-DP / ZeRO-1/2 gradient "
                "sync; ZeRO-3 shards params per-use and has no full-grad "
                "all-reduce to quantize")
            assert self.optimizer_name != ONEBIT_ADAM_OPTIMIZER, (
                "comm_quantization and OneBitAdam both replace the "
                "gradient all-reduce — enable one comm compressor only")
            assert not self.sparse_gradients_enabled, (
                "comm_quantization is incompatible with sparse_gradients "
                "(the CSR path runs its own per-leaf exchange)")
            assert self.zero_config.cpu_offload is not True, (
                "comm_quantization requires the in-jit update path; "
                "ZeRO-Offload steps the optimizer on host")
        self._check_resilience()
        self._check_elasticity()
        self._check_analysis()
        self._check_telemetry()
        self._check_tensor_parallel()
        self._check_zero3()
        self._check_fp8()
        self._check_inference()

    def _check_inference(self):
        from deepspeed_tpu.runtime.comm.codecs import CODECS
        inf = self.inference
        unknown = sorted(set(inf._given_keys) - set(inf.KEYS))
        if unknown:
            raise ValueError(
                f"inference: unknown key(s) {unknown}; "
                f"allowed: {sorted(inf.KEYS)}")
        mb = inf.max_batch
        if isinstance(mb, bool) or not isinstance(mb, int) or mb < 1:
            raise ValueError(
                f"inference: max_batch must be an int >= 1, got {mb!r}")
        pc = inf.prefill_chunk
        if isinstance(pc, bool) or not isinstance(pc, int) or pc < 1:
            raise ValueError(
                f"inference: prefill_chunk must be an int >= 1, "
                f"got {pc!r}")
        buckets = inf.seq_buckets
        if not buckets:
            raise ValueError("inference: seq_buckets must be non-empty")
        prev = 0
        for b in buckets:
            if isinstance(b, bool) or not isinstance(b, int) or b < 1:
                raise ValueError(
                    f"inference: seq_buckets must be positive ints, "
                    f"got {b!r}")
            if b <= prev:
                raise ValueError(
                    f"inference: seq_buckets must be strictly increasing,"
                    f" got {list(buckets)}")
            if b % pc:
                raise ValueError(
                    f"inference: every seq bucket must be a multiple of "
                    f"prefill_chunk={pc}; got bucket {b}")
            prev = b
        kvd = inf.kv_cache_dtype
        if kvd is not None and kvd not in ("bf16", "f32", "fp32") \
                and kvd not in CODECS:
            raise ValueError(
                f"inference: kv_cache_dtype must be None, 'bf16', 'f32',"
                f" or a codec name from {sorted(CODECS)}; got {kvd!r}")
        mn = inf.max_new_tokens
        if isinstance(mn, bool) or not isinstance(mn, int) or mn < 1:
            raise ValueError(
                f"inference: max_new_tokens must be an int >= 1, "
                f"got {mn!r}")
        if inf.attention_impl not in ("dense", "flash"):
            raise ValueError(
                f"inference: attention_impl must be 'dense' or 'flash', "
                f"got {inf.attention_impl!r}")
        bk = inf.attention_block_k
        if isinstance(bk, bool) or not isinstance(bk, int) or bk < 1:
            raise ValueError(
                f"inference: attention_block_k must be an int >= 1, "
                f"got {bk!r}")
        temp = inf.temperature
        if isinstance(temp, bool) or \
                not isinstance(temp, (int, float)) or temp < 0:
            raise ValueError(
                f"inference: temperature must be a number >= 0, "
                f"got {temp!r}")
        tk = inf.top_k
        if isinstance(tk, bool) or not isinstance(tk, int) or tk < 0:
            raise ValueError(
                f"inference: top_k must be an int >= 0, got {tk!r}")
        tp = inf.top_p
        if isinstance(tp, bool) or not isinstance(tp, (int, float)) \
                or not 0 < tp <= 1:
            raise ValueError(
                f"inference: top_p must be in (0, 1], got {tp!r}")
        seed = inf.sampling_seed
        if isinstance(seed, bool) or not isinstance(seed, int):
            raise ValueError(
                f"inference: sampling_seed must be an int, got {seed!r}")
        if inf.kv_layout not in ("ring", "paged"):
            raise ValueError(
                f"inference: kv_layout must be 'ring' or 'paged', "
                f"got {inf.kv_layout!r}")
        ps = inf.page_size
        if isinstance(ps, bool) or not isinstance(ps, int) or ps < 0:
            raise ValueError(
                f"inference: page_size must be an int >= 0 (0 = auto), "
                f"got {ps!r}")
        if inf.kv_layout == "paged" and ps:
            if ps % pc:
                raise ValueError(
                    f"inference: page_size must be a multiple of "
                    f"prefill_chunk={pc}; got {ps}")
            if max(buckets) % ps:
                raise ValueError(
                    f"inference: page_size must divide the largest seq "
                    f"bucket {max(buckets)}; got {ps}")
        npg = inf.n_pages
        if isinstance(npg, bool) or not isinstance(npg, int) or npg < 0:
            raise ValueError(
                f"inference: n_pages must be an int >= 0 (0 = auto), "
                f"got {npg!r}")
        if npg == 1:
            raise ValueError(
                "inference: n_pages must be >= 2 when set (page 0 is "
                "the reserved trash page); got 1")
        if not isinstance(inf.prefix_cache, bool):
            raise ValueError(
                f"inference: prefix_cache must be a bool, "
                f"got {inf.prefix_cache!r}")
        hp = inf.host_park_threshold
        if isinstance(hp, bool) or not isinstance(hp, (int, float)) \
                or not 0 <= hp < 1:
            raise ValueError(
                f"inference: host_park_threshold must be in [0, 1), "
                f"got {hp!r}")
        nr = inf.replicas
        if isinstance(nr, bool) or not isinstance(nr, int) or nr < 1:
            raise ValueError(
                f"inference: replicas must be an int >= 1, got {nr!r}")
        mrd = inf.max_redispatch
        if isinstance(mrd, bool) or not isinstance(mrd, int) or mrd < 0:
            raise ValueError(
                f"inference: max_redispatch must be an int >= 0, "
                f"got {mrd!r}")
        mqd = inf.max_queue_depth
        if isinstance(mqd, bool) or not isinstance(mqd, int) or mqd < 1:
            raise ValueError(
                f"inference: max_queue_depth must be an int >= 1, "
                f"got {mqd!r}")
        for name, val in (("deadline_s", inf.deadline_s),
                          ("queue_timeout_s", inf.queue_timeout_s)):
            if val is None:
                continue        # also "disabled", like 0
            if isinstance(val, bool) or \
                    not isinstance(val, (int, float)) or val < 0:
                raise ValueError(
                    f"inference: {name} must be a number >= 0 "
                    f"(0 = disabled), got {val!r}")
        if not isinstance(inf.disaggregated, bool):
            raise ValueError(
                f"inference: disaggregated must be a bool, "
                f"got {inf.disaggregated!r}")
        for name, val in (("prefill_workers", inf.prefill_workers),
                          ("decode_workers", inf.decode_workers)):
            if isinstance(val, bool) or not isinstance(val, int) \
                    or val < 1:
                raise ValueError(
                    f"inference: {name} must be an int >= 1, "
                    f"got {val!r}")
        for name, val in (("prefill_max_batch", inf.prefill_max_batch),
                          ("decode_max_batch", inf.decode_max_batch)):
            if isinstance(val, bool) or not isinstance(val, int) \
                    or val < 0:
                raise ValueError(
                    f"inference: {name} must be an int >= 0 "
                    f"(0 = max_batch), got {val!r}")
        if inf.disaggregated:
            if inf.kv_layout != "paged":
                raise ValueError(
                    "inference: disaggregated serving requires "
                    "kv_layout='paged' — the prefill->decode handoff "
                    "is a KV page copy")
            if inf.replicas > 1:
                raise ValueError(
                    "inference: disaggregated and replicas > 1 are "
                    "mutually exclusive — tiers scale via "
                    "prefill_workers/decode_workers")
            if inf.speculative_enabled:
                raise ValueError(
                    "inference: disaggregated and speculative are "
                    "mutually exclusive — the draft/verify pair would "
                    "break the one-program-per-tier contract")
        if not isinstance(inf._speculative_raw, dict):
            raise ValueError(
                f"inference: speculative must be a dict block, "
                f"got {inf._speculative_raw!r}")
        spec_unknown = sorted(set(inf._speculative_given_keys)
                              - set(inf.SPECULATIVE_KEYS))
        if spec_unknown:
            raise ValueError(
                f"inference: speculative: unknown key(s) {spec_unknown};"
                f" allowed: {sorted(inf.SPECULATIVE_KEYS)}")
        if not isinstance(inf.speculative_enabled, bool):
            raise ValueError(
                f"inference: speculative.enabled must be a bool, "
                f"got {inf.speculative_enabled!r}")
        sk = inf.speculative_k
        if isinstance(sk, bool) or not isinstance(sk, int) or sk < 1:
            # the validated config is strict (k >= 1: a 0-token draft
            # is a misconfiguration, not a mode); only the ENGINE's
            # dict path treats k=0 as a degenerate disable
            raise ValueError(
                f"inference: speculative.k must be an int >= 1, "
                f"got {sk!r}")
        sd = inf.speculative_draft_layers
        if isinstance(sd, bool) or not isinstance(sd, int) or sd < 0:
            raise ValueError(
                f"inference: speculative.draft_layers must be an int "
                f">= 0 (0 = auto: n_layer // 2), got {sd!r}")
        sg = inf.speculative_min_accept_to_grow
        if isinstance(sg, bool) or not isinstance(sg, (int, float)) \
                or sg < 0:
            raise ValueError(
                f"inference: speculative.min_accept_to_grow must be a "
                f"number >= 0, got {sg!r}")
        if inf.speculative_enabled:
            if sk + 1 >= max(buckets):
                # the verify chunk writes k+1 slots per round; a k
                # within one chunk of the largest bucket leaves no
                # room to generate anything
                raise ValueError(
                    f"inference: speculative.k={sk} leaves no headroom "
                    f"in the largest seq bucket {max(buckets)} (need "
                    f"k + 1 < max bucket)")
            if nr > 1:
                # the fleet router's drain/redispatch bookkeeping is
                # written against the 2-program engine; speculative
                # serving is single-replica until the router learns
                # the 3-program contract
                raise ValueError(
                    f"inference: speculative decoding is mutually "
                    f"exclusive with replicas > 1 (got replicas={nr}); "
                    f"run speculative engines single-replica")

    def _check_fp8(self):
        from deepspeed_tpu.runtime.comm.codecs import CODECS
        f8 = self.fp8
        if not isinstance(f8.enabled, bool):
            raise ValueError(
                f"fp8: enabled must be a bool, got {f8.enabled!r}")
        if not isinstance(f8.wire_enabled, bool):
            raise ValueError(
                f"fp8.wire: enabled must be a bool, got "
                f"{f8.wire_enabled!r}")
        if isinstance(f8.margin, bool) or not isinstance(f8.margin, int) \
                or f8.margin < 0:
            raise ValueError(
                f"fp8: margin must be an int >= 0, got {f8.margin!r}")
        hl = f8.amax_history_len
        if isinstance(hl, bool) or not isinstance(hl, int) or hl < 1:
            raise ValueError(
                f"fp8: amax_history_len must be an int >= 1, got {hl!r}")
        if f8.sites is not None:
            if not isinstance(f8.sites, dict):
                raise ValueError(
                    f"fp8: sites must be a dict of per-site overrides, "
                    f"got {f8.sites!r}")
            for site, ov in f8.sites.items():
                if not isinstance(ov, dict):
                    raise ValueError(
                        f"fp8: sites[{site!r}] must be a dict, got {ov!r}")
                for key, v in ov.items():
                    if key != FP8_ENABLED:
                        raise ValueError(
                            f"fp8: unknown key {key!r} in sites[{site!r}];"
                            f" allowed: [{FP8_ENABLED!r}]")
                    if not isinstance(v, bool):
                        raise ValueError(
                            f"fp8: sites[{site!r}].{key} must be a bool, "
                            f"got {v!r}")
        if f8.wire_enabled:
            if f8.wire_dtype not in CODECS:
                raise ValueError(
                    f"fp8.wire: dtype must be one of {sorted(CODECS)}, "
                    f"got {f8.wire_dtype!r}")
            wc = f8.wire_chunk_size
            if isinstance(wc, bool) or not isinstance(wc, int) or wc < 1:
                raise ValueError(
                    f"fp8.wire: chunk_size must be an int >= 1, "
                    f"got {wc!r}")
            if self.comm_quantization.enabled:
                raise ValueError(
                    "fp8.wire and comm_quantization both quantize the "
                    "gradient exchange — enable one comm compressor only")
        if f8.enabled or f8.wire_enabled:
            if self.optimizer_name == ONEBIT_ADAM_OPTIMIZER:
                raise ValueError(
                    "fp8 is incompatible with OneBitAdam (both rewrite "
                    "the gradient exchange/state threading)")
            if self.sparse_gradients_enabled:
                raise ValueError(
                    "fp8 is incompatible with sparse_gradients (the CSR "
                    "path runs its own per-leaf exchange)")
            if self.zero_config.cpu_offload is True:
                raise ValueError(
                    "fp8 requires the in-jit update path; ZeRO-Offload "
                    "steps the optimizer on host")

    def _check_zero3(self):
        zc = self.zero_config

        def _bool(name, v):
            if not isinstance(v, bool):
                raise ValueError(
                    f"zero_optimization: {name} must be a bool, got {v!r}")

        _bool("gather_on_use", zc.gather_on_use)
        _bool("prefetch", zc.prefetch)
        _bool("bidirectional", zc.bidirectional)
        chunks = zc.gather_chunks
        if isinstance(chunks, bool) or not isinstance(chunks, int) or \
                chunks < 1:
            raise ValueError(
                f"zero_optimization: gather_chunks must be an int >= 1, "
                f"got {chunks!r}")
        if chunks > 1 and not zc.prefetch:
            # The prefetch dep-chain doubles as the rendezvous-safety
            # invariant for the ppermute rings: with it off, two stripes'
            # rings could be in flight concurrently.
            raise ValueError(
                "zero_optimization: gather_chunks > 1 requires "
                "prefetch=true (the dep-chain orders the ppermute rings)")
        if chunks > 1 and not zc.gather_on_use:
            raise ValueError(
                "zero_optimization: gather_chunks > 1 requires "
                "gather_on_use=true (the legacy spec-sharded path has no "
                "ring schedule to chunk)")

    def _check_tensor_parallel(self):
        from deepspeed_tpu.parallel.collectives import OVERLAP_SITES
        tp = self.tensor_parallel

        def _bool(name, v):
            if not isinstance(v, bool):
                raise ValueError(
                    f"tensor_parallel.overlap: {name} must be a bool, "
                    f"got {v!r}")

        def _chunks(name, v):
            if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                raise ValueError(
                    f"tensor_parallel.overlap: {name} must be an int >= 1,"
                    f" got {v!r}")

        def _wire(name, v):
            if v is None:
                return
            from deepspeed_tpu.runtime.comm.codecs import CODECS
            if v not in CODECS:
                raise ValueError(
                    f"tensor_parallel.overlap: {name} must be one of "
                    f"{sorted(CODECS)} (or null), got {v!r}")

        _bool("enabled", tp.overlap_enabled)
        _bool("bidirectional", tp.overlap_bidirectional)
        _chunks("chunks", tp.overlap_chunks)
        _wire("wire_dtype", tp.overlap_wire_dtype)
        _chunks("wire_chunk", tp.overlap_wire_chunk)
        sites = tp.overlap_sites
        if sites is None:
            return
        if not isinstance(sites, dict):
            raise ValueError(
                f"tensor_parallel.overlap: sites must be a dict of "
                f"per-site overrides, got {sites!r}")
        for site, ov in sites.items():
            if site not in OVERLAP_SITES:
                raise ValueError(
                    f"tensor_parallel.overlap: unknown site {site!r}; "
                    f"known: {list(OVERLAP_SITES)}")
            if not isinstance(ov, dict):
                raise ValueError(
                    f"tensor_parallel.overlap: sites[{site!r}] must be a "
                    f"dict, got {ov!r}")
            for key, v in ov.items():
                if key == TP_OVERLAP_ENABLED or \
                        key == TP_OVERLAP_BIDIRECTIONAL:
                    _bool(f"sites[{site!r}].{key}", v)
                elif key == TP_OVERLAP_CHUNKS or \
                        key == TP_OVERLAP_WIRE_CHUNK:
                    _chunks(f"sites[{site!r}].{key}", v)
                elif key == TP_OVERLAP_WIRE_DTYPE:
                    _wire(f"sites[{site!r}].{key}", v)
                else:
                    raise ValueError(
                        f"tensor_parallel.overlap: unknown key {key!r} in "
                        f"sites[{site!r}]; allowed: "
                        f"[{TP_OVERLAP_ENABLED!r}, {TP_OVERLAP_CHUNKS!r}, "
                        f"{TP_OVERLAP_BIDIRECTIONAL!r}, "
                        f"{TP_OVERLAP_WIRE_DTYPE!r}, "
                        f"{TP_OVERLAP_WIRE_CHUNK!r}]")

    def _check_analysis(self):
        from deepspeed_tpu.analysis.rules import RULE_IDS
        an = self.analysis
        for name, v in (("enabled", an.enabled),
                        ("fail_on_findings", an.fail_on_findings),
                        ("check_recompile", an.check_recompile)):
            if not isinstance(v, bool):
                raise ValueError(
                    f"analysis: {name} must be a bool, got {v!r}")
        if an.rules is not None:
            if not isinstance(an.rules, (list, tuple)) or \
                    not all(isinstance(r, str) for r in an.rules):
                raise ValueError(
                    f"analysis: rules must be a list of rule ids, "
                    f"got {an.rules!r}")
            unknown = sorted(set(an.rules) - set(RULE_IDS))
            if unknown:
                raise ValueError(
                    f"analysis: unknown rule id(s) {unknown}; "
                    f"known: {list(RULE_IDS)}")
        budget = an.peak_memory_budget_mb
        if not isinstance(budget, (int, float)) or \
                isinstance(budget, bool) or budget < 0:
            raise ValueError(
                f"analysis: peak_memory_budget_mb must be a "
                f"non-negative number (0 = per-stage default), "
                f"got {budget!r}")

    def _check_telemetry(self):
        tl = self.telemetry
        unknown = sorted(set(tl._given_keys) - set(tl.KEYS))
        if unknown:
            raise ValueError(
                f"telemetry: unknown key(s) {unknown}; "
                f"allowed: {sorted(tl.KEYS)}")
        for name, v in (("enabled", tl.enabled),
                        ("console", tl.console),
                        ("stamp_static_facts", tl.stamp_static_facts)):
            if not isinstance(v, bool):
                raise ValueError(
                    f"telemetry: {name} must be a bool, got {v!r}")
        for name, v in (("jsonl_path", tl.jsonl_path),
                        ("prometheus_textfile", tl.prometheus_textfile)):
            if v is not None and not isinstance(v, str):
                raise ValueError(
                    f"telemetry: {name} must be a path string or null, "
                    f"got {v!r}")
        for name, v, lo in (
                ("history", tl.history, 1),
                ("prometheus_write_every", tl.prometheus_write_every, 1)):
            if isinstance(v, bool) or not isinstance(v, int) or v < lo:
                raise ValueError(
                    f"telemetry: {name} must be an int >= {lo}, "
                    f"got {v!r}")
        fpt = tl.flops_per_token
        if isinstance(fpt, bool) or \
                not isinstance(fpt, (int, float)) or fpt < 0:
            raise ValueError(
                f"telemetry: flops_per_token must be a non-negative "
                f"number (0 = unknown), got {fpt!r}")
        self._check_telemetry_forensics(tl)

    def _check_telemetry_forensics(self, tl):
        from deepspeed_tpu.telemetry.watchdog import WATCHDOG_ACTIONS
        if tl.crash_dump_dir is not None and \
                not isinstance(tl.crash_dump_dir, str):
            raise ValueError(
                f"telemetry: crash_dump_dir must be a path string or "
                f"null, got {tl.crash_dump_dir!r}")
        fh = tl.flight_history
        if isinstance(fh, bool) or not isinstance(fh, int) or fh < 1:
            raise ValueError(
                f"telemetry: flight_history must be an int >= 1, "
                f"got {fh!r}")
        for label, given, allowed in (
                ("watchdog", tl._watchdog_given_keys, tl.WATCHDOG_KEYS),
                ("anomaly_trace", tl._anomaly_given_keys, tl.ANOMALY_KEYS)):
            unknown = sorted(set(given) - set(allowed))
            if unknown:
                raise ValueError(
                    f"telemetry: unknown {label} key(s) {unknown}; "
                    f"allowed: {sorted(allowed)}")
        for name, v in (("watchdog.enabled", tl.watchdog_enabled),
                        ("anomaly_trace.enabled", tl.anomaly_trace_enabled)):
            if not isinstance(v, bool):
                raise ValueError(
                    f"telemetry: {name} must be a bool, got {v!r}")
        for name, v in (
                ("watchdog.deadline_factor", tl.watchdog_deadline_factor),
                ("watchdog.min_deadline_s", tl.watchdog_min_deadline_s),
                ("anomaly_trace.factor", tl.anomaly_trace_factor)):
            if isinstance(v, bool) or not isinstance(v, (int, float)) \
                    or v <= 0:
                raise ValueError(
                    f"telemetry: {name} must be a positive number, "
                    f"got {v!r}")
        if tl.watchdog_action not in WATCHDOG_ACTIONS:
            raise ValueError(
                f"telemetry: watchdog.action must be one of "
                f"{list(WATCHDOG_ACTIONS)}, got {tl.watchdog_action!r}")
        for name, v in (
                ("anomaly_trace.window", tl.anomaly_trace_window),
                ("anomaly_trace.capture_steps",
                 tl.anomaly_trace_capture_steps)):
            if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                raise ValueError(
                    f"telemetry: {name} must be an int >= 1, got {v!r}")
        if tl.watchdog_enabled and not tl.crash_dump_dir:
            raise ValueError(
                "telemetry: watchdog.enabled requires crash_dump_dir — "
                "the watchdog writes its heartbeat files and flight "
                "dumps there")

    def _check_elasticity(self):
        from deepspeed_tpu.runtime.elastic.batch import LR_SCALING_RULES
        el = self.elasticity
        if el.max_world_size and el.max_world_size < 0:
            raise ValueError(
                f"elasticity: max_world_size must be >= 0 (0 = unbounded),"
                f" got {el.max_world_size}")
        if not el.enabled:
            return
        if el.lr_scaling not in LR_SCALING_RULES:
            raise ValueError(
                f"elasticity: lr_scaling must be one of {LR_SCALING_RULES},"
                f" got {el.lr_scaling!r}")
        if el.target_global_batch is not None and el.target_global_batch <= 0:
            raise ValueError(
                f"elasticity: target_global_batch must be > 0, "
                f"got {el.target_global_batch}")
        if el.max_world_size and self.world_size > el.max_world_size:
            raise ValueError(
                f"elasticity: world size {self.world_size} exceeds "
                f"max_world_size {el.max_world_size}")

    def _check_resilience(self):
        from deepspeed_tpu.runtime.resilience.guards import (
            ACTION_ROLLBACK, ACTION_SKIP_STEP, VALID_ACTIONS)
        rz = self.resilience
        if rz.auto_resume and not rz.save_dir:
            raise ValueError(
                "resilience: auto_resume requires save_dir — there is "
                "nowhere to discover checkpoints from")
        if rz.save_interval_steps and not rz.save_dir:
            raise ValueError(
                "resilience: save_interval_steps requires save_dir")
        if rz.save_interval_steps < 0:
            raise ValueError(
                f"resilience: save_interval_steps must be >= 0, "
                f"got {rz.save_interval_steps}")
        if rz.keep_last_n < 0:
            raise ValueError(
                f"resilience: checkpoint.keep_last_n must be >= 0 "
                f"(0 keeps everything), got {rz.keep_last_n}")
        if rz.io_retries < 1:
            raise ValueError(
                f"resilience: checkpoint.io_retries must be >= 1, "
                f"got {rz.io_retries}")
        guard_actions = {
            "nan_grads": rz.nan_guard_action,
            "loss_spike": rz.loss_spike_action,
            "scale_collapse": rz.scale_collapse_action,
        }
        for guard, action in guard_actions.items():
            if action is None:
                continue
            if action not in VALID_ACTIONS:
                raise ValueError(
                    f"resilience: guards.{guard}.action must be one of "
                    f"{list(VALID_ACTIONS)} (or omitted to disable), "
                    f"got {action!r}")
            if action == ACTION_ROLLBACK and not rz.save_dir:
                raise ValueError(
                    f"resilience: guards.{guard}.action="
                    f"'rollback_to_checkpoint' requires save_dir — there "
                    "is no checkpoint to roll back to")
            if action == ACTION_SKIP_STEP and guard != "nan_grads":
                raise ValueError(
                    f"resilience: guards.{guard} detects the problem only "
                    "after the update has been applied, so 'skip_step' is "
                    "impossible — use 'warn', 'rollback_to_checkpoint' or "
                    "'abort'")
        if rz.scale_collapse_action is not None and not self.fp16_enabled:
            raise ValueError(
                "resilience: guards.scale_collapse watches the dynamic "
                "fp16 loss scale; it requires fp16 to be enabled")
        if rz.loss_spike_action is not None and \
                rz.loss_spike_min_history < 1:
            raise ValueError(
                f"resilience: guards.loss_spike.min_history must be >= 1, "
                f"got {rz.loss_spike_min_history}")
        if rz.scale_collapse_action is not None and \
                rz.scale_collapse_patience < 1:
            raise ValueError(
                f"resilience: guards.scale_collapse.patience must be >= 1, "
                f"got {rz.scale_collapse_patience}")
        if rz.hot_enabled:
            if rz.hot_interval_steps < 1:
                raise ValueError(
                    f"resilience: hot_checkpoint.interval_steps must be "
                    f">= 1 when the tier is enabled, "
                    f"got {rz.hot_interval_steps}")
            if rz.hot_capacity < 1:
                raise ValueError(
                    f"resilience: hot_checkpoint.capacity must be >= 1, "
                    f"got {rz.hot_capacity}")
            if rz.hot_mirror_keep < 1:
                raise ValueError(
                    f"resilience: hot_checkpoint.mirror_keep must be "
                    f">= 1, got {rz.hot_mirror_keep}")

    def _do_warning_check(self):
        fp16_enabled = self.fp16_enabled
        vocabulary_size = self.vocabulary_size
        if vocabulary_size and vocabulary_size % TENSOR_CORE_ALIGN_SIZE != 0:
            logger.warning(
                "DeepSpeedConfig: vocabulary size {} is not aligned to {}, "
                "may import tensor core utilization.".format(
                    vocabulary_size, TENSOR_CORE_ALIGN_SIZE))
        if self.optimizer_params is not None and \
                MAX_GRAD_NORM in self.optimizer_params.keys() and \
                self.optimizer_params[MAX_GRAD_NORM] > 0:
            if fp16_enabled:
                logger.warning(
                    "DeepSpeedConfig: In FP16 mode, DeepSpeed will pass {}:{} "
                    "to FP16 wrapper".format(MAX_GRAD_NORM,
                                             self.optimizer_params[MAX_GRAD_NORM]))
            else:
                logger.warning(
                    "DeepSpeedConfig: In FP32 mode, DeepSpeed does not permit "
                    "MAX_GRAD_NORM ({}) > 0, setting to zero".format(
                        self.optimizer_params[MAX_GRAD_NORM]))
                self.optimizer_params[MAX_GRAD_NORM] = 0.0

    def print(self, name):
        logger.info("{}:".format(name))
        for arg in sorted(vars(self)):
            if arg != "_param_dict":
                dots = "." * (29 - len(arg))
                logger.info("  {} {} {}".format(arg, dots, getattr(self, arg)))
        logger.info("  json = {}".format(
            json.dumps(self._param_dict, sort_keys=True, indent=4,
                       separators=(",", ":"))))
