"""`ds_tpu_lint`: the repo's enforced lint gate.

Prefers ``ruff check`` (config in pyproject.toml's ``[tool.ruff]``
block) when a ruff binary or module is importable; otherwise falls back
to a dependency-free subset of the same gate so the check is *always*
enforceable in minimal containers:

- ``E9``: the file must compile (``compile(...)``) — syntax errors.
- ``W291``/``W293``: trailing whitespace (on code / on blank lines).
- ``W292``: missing final newline.

The fallback intentionally mirrors rule ids ruff would emit so findings
read the same either way, and ``--fix`` repairs the whitespace classes
in place. Exit 0 clean, 1 findings, 2 usage error — the same contract
as ``ds_tpu_audit`` so CI can treat both as gates.
"""

import argparse
import os
import shutil
import subprocess
import sys

# What the gate covers by default: the package, its tests and bench
# driver, and the bin/ front scripts (python files without .py).
DEFAULT_PATHS = ("deepspeed_tpu", "tests", "bench.py", "bin", "setup.py")


def repo_root():
    """The checkout root: the directory holding this package's parent."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _ruff_argv():
    """argv prefix for a usable ruff, or None. Binary first, then the
    pip-installed module form (``python -m ruff``)."""
    exe = shutil.which("ruff")
    if exe:
        return [exe]
    try:
        import ruff  # noqa: F401
    except Exception:
        return None
    return [sys.executable, "-m", "ruff"]


def iter_python_files(paths, root):
    """Yield python files under ``paths`` (relative to ``root``): .py
    files plus extensionless scripts whose shebang mentions python."""
    for path in paths:
        full = path if os.path.isabs(path) else os.path.join(root, path)
        if os.path.isfile(full):
            yield full
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git", "related")]
            for name in sorted(filenames):
                fp = os.path.join(dirpath, name)
                if name.endswith(".py"):
                    yield fp
                elif "." not in name:
                    try:
                        with open(fp, "rb") as f:
                            first = f.readline()
                    except OSError:
                        continue
                    if first.startswith(b"#!") and b"python" in first:
                        yield fp


def check_file(path, fix=False):
    """Fallback checks for one file → list of (line, code, message).
    ``fix=True`` rewrites the whitespace findings in place (syntax
    errors are only ever reported)."""
    findings = []
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except (OSError, UnicodeDecodeError) as exc:
        return [(0, "E902", f"cannot read: {exc}")]

    if path.endswith(".py") or "\n" in text[:200]:
        try:
            compile(text, path, "exec")
        except SyntaxError as exc:
            findings.append((exc.lineno or 0, "E999",
                             f"syntax error: {exc.msg}"))

    lines = text.split("\n")
    fixed = []
    for i, line in enumerate(lines, 1):
        stripped = line.rstrip(" \t")
        if stripped != line:
            code = "W293" if not stripped else "W291"
            what = ("whitespace on blank line" if code == "W293"
                    else "trailing whitespace")
            findings.append((i, code, what))
        fixed.append(stripped)
    if text and not text.endswith("\n"):
        findings.append((len(lines), "W292", "no newline at end of file"))

    if fix:
        new = "\n".join(fixed)
        if new and not new.endswith("\n"):
            new += "\n"
        if new != text:
            with open(path, "w", encoding="utf-8") as f:
                f.write(new)
    return findings


def run_fallback(paths, root, fix=False):
    n_files, n_findings = 0, 0
    for fp in iter_python_files(paths, root):
        n_files += 1
        for line, code, msg in check_file(fp, fix=fix):
            n_findings += 1
            rel = os.path.relpath(fp, root)
            print(f"{rel}:{line}: {code} {msg}")
    tag = " (after --fix)" if fix else ""
    print(f"ds_tpu_lint[builtin]: {n_files} file(s), "
          f"{n_findings} finding(s){tag}")
    return 1 if n_findings else 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_tpu_lint",
        description="Repo lint gate: ruff when available, a built-in "
                    "whitespace/syntax subset otherwise.")
    parser.add_argument("paths", nargs="*", default=None,
                        help=f"files/dirs to lint (default: "
                             f"{', '.join(DEFAULT_PATHS)})")
    parser.add_argument("--fix", action="store_true",
                        help="auto-fix what the active backend can "
                             "(ruff --fix; builtin: whitespace)")
    parser.add_argument("--builtin", action="store_true",
                        help="force the dependency-free fallback even "
                             "if ruff is installed")
    args = parser.parse_args(argv)

    root = repo_root()
    paths = args.paths or list(DEFAULT_PATHS)

    ruff = None if args.builtin else _ruff_argv()
    if ruff is not None:
        cmd = ruff + ["check"] + (["--fix"] if args.fix else []) + paths
        proc = subprocess.run(cmd, cwd=root)
        return proc.returncode
    return run_fallback(paths, root, fix=args.fix)


if __name__ == "__main__":
    sys.exit(main())
