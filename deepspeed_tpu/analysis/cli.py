"""`ds_tpu_audit`: audit compiled train steps from the command line.

Three modes:

- ``ds_tpu_audit --flavors dense,zero1`` (default: all seven stock
  flavors) — build toy engines per flavor and audit each compiled step.
- ``ds_tpu_audit --config my_config.json`` — build an engine from a
  user DeepSpeed-style config (with a toy GPT-2 model supplying the
  loss) and audit the step that config actually compiles to.
- ``ds_tpu_audit --hlo dump.txt`` — run the HLO-text rule subset over a
  saved HLO dump (no engine, no trace; the jaxpr-level rules don't run).

``--memory`` appends the static peak-memory table per audited step
(liveness peak, temp peak, parameter/output/donated bytes from
``analysis.hlo.estimate_peak_memory``).

Reports findings as text (default) or JSON (``--json``); exits non-zero
when findings at or above ``--fail-on`` severity (default ``error``)
exist. Runs on CPU by default (``JAX_PLATFORMS=cpu`` unless the caller
overrides) — the audit reads compile-time artifacts, so no TPU needed.
"""

import argparse
import json
import os
import sys


def _build_config_engine(config_path, compilation_cache_dir=None):
    """Engine for a user config: toy GPT-2 supplies model/loss (pipeline
    configs need a PipelineModule and aren't supported here — use
    ``--flavors pipeline`` for the stock pipeline audit)."""
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (GPT2LMHead, gpt2_tiny,
                                           init_gpt2_params,
                                           make_gpt2_loss_fn)
    import numpy as np

    with open(config_path) as f:
        cfg = json.load(f)
    if compilation_cache_dir:
        cfg["compilation_cache_dir"] = compilation_cache_dir
    model = GPT2LMHead(gpt2_tiny())
    params = init_gpt2_params(model, jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, loss_fn=make_gpt2_loss_fn(model), params=params)
    rows = int(cfg.get("train_batch_size", 8))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 255, (rows, 32)).astype(np.int32)}
    return engine, batch


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_tpu_audit",
        description="Static audit of compiled train steps: donation/"
                    "aliasing, ZeRO byte budgets, dtype hygiene, host "
                    "transfers, trip-count-aware collective accounting, "
                    "recompile detection.")
    parser.add_argument("--config", default=None,
                        help="DeepSpeed-style JSON config to audit "
                             "(engine built with a toy GPT-2 model)")
    parser.add_argument("--hlo", default=None, metavar="FILE",
                        help="audit a saved HLO text dump instead of "
                             "building an engine (HLO-text rules only)")
    parser.add_argument("--memory", action="store_true",
                        help="print the static peak-memory table per "
                             "audited step (text mode; JSON always "
                             "carries it in stats.peak_memory)")
    parser.add_argument("--flavors", default=None,
                        help="comma-separated stock flavors to audit "
                             "(default: all seven); extra flavors like "
                             "pipeline_tp (TP overlap) must be named "
                             "explicitly; ignored with --config")
    parser.add_argument("--kernels", action="store_true",
                        help="run the sub-pallas_call kernel analyzer "
                             "sweep (analysis/kernels.py) instead of "
                             "the train-step flavors: VMEM budgets, "
                             "tile-alignment lint, DMA-elision proofs "
                             "and grid-write races over flash_train, "
                             "decode_ring, decode_paged, speculative; "
                             "--flavors selects a subset of those")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: full catalog)")
    parser.add_argument("--steps", type=int, default=0,
                        help="extra train steps to run for the recompile "
                             "detector (default 0)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit a JSON report instead of text")
    parser.add_argument("--fail-on", default="error",
                        choices=("error", "warning"),
                        help="exit non-zero on findings at/above this "
                             "severity (default: error)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--compilation-cache-dir", default=None,
                        metavar="DIR",
                        help="persistent XLA compile cache for the "
                             "audited engines (repeat audits become "
                             "cache hits)")
    args = parser.parse_args(argv)

    # Audits read compile-time artifacts; default to the CPU backend
    # (and an 8-device virtual mesh for the sharded flavors) so this
    # runs anywhere. Must happen before jax import.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "cpu" in os.environ.get("JAX_PLATFORMS", "") \
            and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()

    if args.compilation_cache_dir:
        # toy audits compile in under jax's default persistence
        # threshold (1s); cache them anyway so reruns are hits
        import jax
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)

    from deepspeed_tpu.analysis.rules import RULE_IDS, SEV_ERROR
    if args.list_rules:
        from deepspeed_tpu.analysis import rules as rules_mod
        for rule_id in RULE_IDS:
            fn = rules_mod.RULES.get(rule_id)
            doc = (fn.__doc__ or "recompile detector (orchestrator-level)"
                   ).strip().splitlines()[0]
            print(f"{rule_id:16s} {doc}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = sorted(set(rules) - set(RULE_IDS))
        if unknown:
            parser.error(f"unknown rule id(s) {unknown}; "
                         f"known: {list(RULE_IDS)}")

    from deepspeed_tpu.analysis.audit import (EXTRA_FLAVORS, STEP_FLAVORS,
                                              audit_decode, audit_engine,
                                              audit_flash_train,
                                              audit_flavors, audit_hlo,
                                              audit_kernel_flavors,
                                              audit_speculative)
    if args.hlo and args.config:
        parser.error("--hlo and --config are mutually exclusive")
    if args.kernels and (args.hlo or args.config):
        parser.error("--kernels audits the stock kernel flavors; it "
                     "does not combine with --hlo/--config")
    if args.kernels:
        kernel_sweep = {
            "flash_train": lambda: audit_flash_train(rules=rules),
            "decode_ring": lambda: audit_decode(
                rules=rules, kv_layout="ring", kernels=True),
            "decode_paged": lambda: audit_decode(
                rules=rules, kv_layout="paged", kernels=True),
            "speculative": lambda: audit_speculative(
                rules=rules, kernels=True),
        }
        if args.flavors:
            names = [f.strip() for f in args.flavors.split(",")
                     if f.strip()]
            unknown = sorted(set(names) - set(kernel_sweep))
            if unknown:
                parser.error(f"unknown kernel flavor(s) {unknown}; "
                             f"known: {list(kernel_sweep)}")
            reports = {name: kernel_sweep[name]() for name in names}
            for name, rep in reports.items():
                rep.flavor = name
        else:
            reports = audit_kernel_flavors(rules=rules)
    elif args.hlo:
        try:
            with open(args.hlo) as f:
                hlo_text = f.read()
        except OSError as exc:
            parser.error(f"cannot read --hlo file: {exc}")
        reports = {"hlo": audit_hlo(hlo_text, rules=rules)}
    elif args.config:
        engine, batch = _build_config_engine(
            args.config,
            compilation_cache_dir=args.compilation_cache_dir)
        reports = {"config": audit_engine(engine, batch, rules=rules,
                                          steps=args.steps)}
    else:
        flavors = STEP_FLAVORS
        if args.flavors:
            flavors = [f.strip() for f in args.flavors.split(",")
                       if f.strip()]
            known = STEP_FLAVORS + EXTRA_FLAVORS
            unknown = sorted(set(flavors) - set(known))
            if unknown:
                parser.error(f"unknown flavor(s) {unknown}; "
                             f"known: {list(known)}")
        overrides = None
        if args.compilation_cache_dir:
            overrides = {
                "compilation_cache_dir": args.compilation_cache_dir}
        reports = audit_flavors(flavors, rules=rules, steps=args.steps,
                                config_overrides=overrides)

    fail_severities = {"error": (SEV_ERROR,),
                       "warning": (SEV_ERROR, "warning")}[args.fail_on]
    n_failing = sum(1 for rep in reports.values() for f in rep.findings
                    if f.severity in fail_severities)
    n_findings = sum(len(rep.findings) for rep in reports.values())

    if args.as_json:
        # Same schema tag as the telemetry event log so downstream
        # tooling can join audit output with run telemetry by version.
        from deepspeed_tpu.telemetry.events import SCHEMA_VERSION
        print(json.dumps(
            {"schema": SCHEMA_VERSION,
             "reports": {k: rep.to_dict() for k, rep in reports.items()},
             "findings_total": n_findings,
             "failing_findings": n_failing,
             "fail_on": args.fail_on,
             "ok": n_failing == 0},
            indent=2, sort_keys=True))
    else:
        for rep in reports.values():
            print(rep.to_text())
        if args.memory:
            print("\nstatic peak memory (analysis.hlo.estimate_peak_"
                  "memory):")
            cols = ("peak_bytes", "temp_peak_bytes", "parameter_bytes",
                    "output_bytes", "donated_output_bytes")
            head = "step".ljust(12) + "".join(
                c.replace("donated_output", "donated")
                 .replace("_bytes", "").rjust(12) for c in cols)
            print(head)
            for name, rep in reports.items():
                pm = (rep.stats or {}).get("peak_memory") or {}
                row = name.ljust(12) + "".join(
                    f"{pm.get(c, 0) / (1 << 20):11.2f}M" for c in cols)
                print(row)
        print(f"\n{len(reports)} step(s) audited, {n_findings} "
              f"finding(s), {n_failing} at/above --fail-on="
              f"{args.fail_on}")
    return 1 if n_failing else 0


if __name__ == "__main__":
    sys.exit(main())
