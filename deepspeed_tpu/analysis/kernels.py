"""Static analysis below the ``pallas_call`` boundary.

The audit subsystem verifies compiled XLA programs (collectives,
donation, peak memory, deadlocks) but its HLO/jaxpr rules stop at the
``pallas_call`` primitive — exactly where the performance-critical
serving and attention code lives (`ops/pallas/flash_attention.py`,
`ops/pallas/flash_decode.py`, `ops/pallas/fused_adam.py`). This module
walks a traced step (or serving program), extracts every
``pallas_call`` equation from the jaxpr, and checks four per-kernel
properties the Mosaic compiler will not check for us:

1. **VMEM footprint** — the per-grid-step working set: every
   BlockSpec's block shape x dtype width for inputs and outputs
   (doubled for Pallas's pipelined double buffering) plus the declared
   scratch shapes, against the per-platform VMEM budget in
   `analysis/cost.py`'s constants table (:data:`cost.PLATFORMS`,
   ``vmem_bytes``). A block configuration that cannot fit is a
   compile-time failure on real hardware that interpret-mode CI would
   never see.

2. **Tile-alignment lint** — block trailing dims vs the TPU native
   tile for the operand dtype (8x128 f32, 16x128 bf16, 32x128
   int8/fp8). A block whose lane (last) dim is not a multiple of 128,
   or whose sublane (second-minor) dim is not a multiple of the
   dtype's sublane count, wastes register tiles on every touch.
   Geometry-forced shapes are exempt: a block dim that covers the full
   array dim was never a choice, and singleton dims are indexed, not
   tiled.

3. **DMA-elision proofs** — each operand's index map is evaluated
   CONCRETELY over the full grid (index maps are pure functions of the
   grid indices and the scalar-prefetch operands, which the analyzer
   captures as live values by interpreting the traced jaxpr). Pallas
   skips the copy when consecutive grid steps map to the same block,
   so counting distinct-vs-total physical blocks per operand *proves*
   the flash-decode clamp trick (`flash_decode.py` ``kv_map`` /
   ``_physical``: clamp the logical block to the row's occupancy, then
   look up the page) actually dedupes dead blocks — and prices the
   kernel's real HBM traffic for the cost model.

4. **Grid-write races** — an output block revisited at NON-consecutive
   grid steps is undefined behavior in Pallas's grid semantics (the
   block is flushed when the grid moves away and re-fetched stale).
   Consecutive revisits are the legitimate accumulator idiom (the
   flash kernels' ``(bh, qi, 0)`` output maps) and pass.

`analysis/rules.py` turns these facts into ``kernel_vmem`` /
``kernel_tiling`` / ``kernel_dma`` findings; `analysis/audit.py` runs
them over the serving flavors and the train flash-attention path
(``ds_tpu_audit --kernels``).
"""

import dataclasses
import time
from typing import Optional

import numpy as np

from deepspeed_tpu.analysis.cost import resolve_platform

# TPU native register tile: (sublane, lane) per element width. The lane
# dim is 128 for every dtype; sublanes scale inversely with width.
LANE = 128
SUBLANES = {4: 8, 2: 16, 1: 32}

# Pallas pipelines block copies: while the grid computes on one block
# the next one streams in, so each input/output block is resident twice.
DOUBLE_BUFFER = 2

# Grids bigger than this skip the concrete index-map sweep (the static
# checks still run); every stock kernel's toy audit grid is far below.
DEFAULT_GRID_POINT_CAP = 65536


def sublane_tile(dtype) -> int:
    """Native sublane count for ``dtype`` (8 f32, 16 bf16, 32 int8/fp8)."""
    return SUBLANES.get(np.dtype(dtype).itemsize, 8)


@dataclasses.dataclass
class OperandFacts:
    """One block-mapped operand (input or output) of a pallas_call."""
    name: str
    kind: str                    # "input" | "output"
    block_shape: tuple
    array_shape: tuple
    dtype: str
    block_bytes: int
    total_fetches: int           # grid points (one block touch each)
    distinct_blocks: int         # unique block indices over the grid
    dma_fetches: int             # after consecutive-step elision
    elided_fraction: float       # 1 - dma_fetches / total_fetches
    index_map_evaluated: bool

    def to_dict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class KernelFacts:
    """Everything the kernel rules check about one pallas_call."""
    name: str
    grid: tuple
    operands: list               # [OperandFacts]
    scratch_bytes: int
    block_bytes_per_step: int    # single-buffered in+out working set
    vmem_bytes: int              # double-buffered blocks + scratch
    dense_bytes: int             # every grid step pays its block DMA
    dma_bytes: int               # after consecutive-step elision
    races: list                  # [{operand, block, steps}]
    tiling: list                 # [{operand, axis, block_dim, ...}]
    notes: list

    @property
    def elided_fraction(self):
        if not self.dense_bytes:
            return 0.0
        return 1.0 - self.dma_bytes / self.dense_bytes

    def to_dict(self):
        return {
            "name": self.name,
            "grid": list(self.grid),
            "scratch_bytes": self.scratch_bytes,
            "block_bytes_per_step": self.block_bytes_per_step,
            "vmem_bytes": self.vmem_bytes,
            "dense_bytes": self.dense_bytes,
            "dma_bytes": self.dma_bytes,
            "elided_dma_fraction": round(self.elided_fraction, 6),
            "races": list(self.races),
            "tiling": list(self.tiling),
            "notes": list(self.notes),
            "operands": {op.name: op.to_dict() for op in self.operands},
        }


@dataclasses.dataclass
class KernelAnalysis:
    """All kernels of one traced program + the platform budget."""
    kernels: list                # [KernelFacts]
    platform: str
    vmem_budget_bytes: int
    wall_s: float
    notes: list

    @property
    def dma_bytes(self):
        return sum(k.dma_bytes for k in self.kernels)

    @property
    def dense_bytes(self):
        return sum(k.dense_bytes for k in self.kernels)

    def to_dict(self):
        return {
            "platform": self.platform,
            "vmem_budget_bytes": self.vmem_budget_bytes,
            "wall_s": round(self.wall_s, 3),
            "notes": list(self.notes),
            "dma_bytes": self.dma_bytes,
            "dense_bytes": self.dense_bytes,
            "kernels": {k.name: k.to_dict() for k in self.kernels},
        }

    def kernel_cost_facts(self):
        """Per-kernel traffic facts in the shape
        `cost.estimate_step_cost(kernel_facts=...)` prices."""
        return [{"name": k.name, "dma_bytes": k.dma_bytes,
                 "dense_bytes": k.dense_bytes} for k in self.kernels]


# ---------------------------------------------------------------------------
# pallas_call extraction: concrete jaxpr interpretation
# ---------------------------------------------------------------------------

# Call-like primitives worth recursing through when (and only when) a
# pallas_call hides inside; everything else executes via plain bind.
_CALL_JAXPR_KEYS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
    "remat": "jaxpr",
    "checkpoint": "jaxpr",
}


def _as_closed(obj):
    import jax

    if isinstance(obj, jax.core.ClosedJaxpr):
        return obj
    return jax.core.ClosedJaxpr(obj, ())


def _param_jaxprs(params):
    import jax

    out = []
    for v in params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for item in vals:
            if isinstance(item, jax.core.ClosedJaxpr):
                out.append(item.jaxpr)
            elif isinstance(item, jax.core.Jaxpr):
                out.append(item)
    return out


_HAS_PALLAS_CACHE = {}


def _jaxpr_has_pallas(jaxpr):
    key = id(jaxpr)
    hit = _HAS_PALLAS_CACHE.get(key)
    if hit is not None:
        return hit
    _HAS_PALLAS_CACHE[key] = False      # cycle guard
    found = False
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            found = True
            break
        if any(_jaxpr_has_pallas(j) for j in _param_jaxprs(eqn.params)):
            found = True
            break
    _HAS_PALLAS_CACHE[key] = found
    return found


def _eqn_has_pallas(eqn):
    if eqn.primitive.name == "pallas_call":
        return True
    return any(_jaxpr_has_pallas(j) for j in _param_jaxprs(eqn.params))


def _bind(eqn, invals):
    subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
    return eqn.primitive.bind(*subfuns, *invals, **bind_params)


def _interp_jaxpr(jaxpr, consts, args, hits):
    """Forward-evaluate ``jaxpr`` with concrete ``args``, recording
    ``(eqn, concrete_invals)`` for every pallas_call reached (first
    occurrence per equation — scan iterations share one). Sub-jaxprs
    are only interpreted when a pallas_call hides inside; everything
    else runs as one compiled bind."""
    import jax

    env = {}

    def read(v):
        return v.val if isinstance(v, jax.core.Literal) else env[v]

    for var, val in zip(jaxpr.constvars, consts):
        env[var] = val
    for var, val in zip(jaxpr.invars, args):
        env[var] = val

    for eqn in jaxpr.eqns:
        invals = [read(v) for v in eqn.invars]
        name = eqn.primitive.name
        outvals = None
        if name == "pallas_call":
            if not any(rec[0] is eqn for rec in hits):
                hits.append((eqn, invals))
            outvals = _bind(eqn, invals)
        elif _eqn_has_pallas(eqn):
            try:
                if name == "scan":
                    outvals = _interp_scan(eqn, invals, hits)
                elif name == "while":
                    outvals = _interp_while(eqn, invals, hits)
                elif name == "cond":
                    branches = eqn.params["branches"]
                    br = branches[int(np.asarray(invals[0]))]
                    outvals = _interp_jaxpr(br.jaxpr, br.consts,
                                            invals[1:], hits)
                elif name in _CALL_JAXPR_KEYS:
                    closed = _as_closed(eqn.params[_CALL_JAXPR_KEYS[name]])
                    outvals = _interp_jaxpr(closed.jaxpr, closed.consts,
                                            invals, hits)
            except Exception:
                outvals = None      # fall through to plain bind
        if outvals is None:
            outvals = _bind(eqn, invals)
        if not eqn.primitive.multiple_results:
            outvals = [outvals]
        for var, val in zip(eqn.outvars, outvals):
            env[var] = val
    return [read(v) for v in jaxpr.outvars]


def _interp_scan(eqn, invals, hits):
    import jax.numpy as jnp

    p = eqn.params
    closed = p["jaxpr"]
    nc, ncar = int(p["num_consts"]), int(p["num_carry"])
    length = int(p["length"])
    consts = invals[:nc]
    carry = list(invals[nc:nc + ncar])
    xs = invals[nc + ncar:]
    order = range(length - 1, -1, -1) if p.get("reverse") else range(length)
    ys_by_i = {}
    for i in order:
        xi = [x[i] for x in xs]
        outs = _interp_jaxpr(closed.jaxpr, closed.consts,
                             [*consts, *carry, *xi], hits)
        carry = list(outs[:ncar])
        ys_by_i[i] = outs[ncar:]
    n_ys = len(next(iter(ys_by_i.values()))) if ys_by_i else 0
    ys = [jnp.stack([ys_by_i[i][j] for i in range(length)])
          for j in range(n_ys)]
    return carry + ys


def _interp_while(eqn, invals, hits, max_iters=100000):
    p = eqn.params
    cond, body = p["cond_jaxpr"], p["body_jaxpr"]
    cn, bn = int(p["cond_nconsts"]), int(p["body_nconsts"])
    cond_consts = invals[:cn]
    body_consts = invals[cn:cn + bn]
    carry = list(invals[cn + bn:])
    for _ in range(max_iters):
        pred = _interp_jaxpr(cond.jaxpr, cond.consts,
                             [*cond_consts, *carry], hits)[0]
        if not bool(np.asarray(pred)):
            return carry
        carry = list(_interp_jaxpr(body.jaxpr, body.consts,
                                   [*body_consts, *carry], hits))
    raise RuntimeError("while loop exceeded the interpreter's iteration "
                       "cap")


def _walk_static(jaxpr, hits, seen):
    """Structural pallas_call sweep (no concrete values) — the fallback
    when the concrete pass is unavailable or fails."""
    if id(jaxpr) in seen:
        return
    seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            if not any(rec[0] is eqn for rec in hits):
                hits.append((eqn, None))
            continue
        for sub in _param_jaxprs(eqn.params):
            _walk_static(sub, hits, seen)


def extract_pallas_calls(fn, args=None):
    """``[(eqn, concrete_invals | None)]`` for every pallas_call in
    ``fn`` traced at ``args``' avals.

    With concrete ``args`` the traced jaxpr is interpreted forward so
    each equation's scalar-prefetch operands are captured as live
    values (what the index-map evaluation needs); tracing alone covers
    programs whose index maps are pure grid functions. Returns the
    extraction plus a note string ("" when the concrete pass ran)."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten(args if args is not None
                                               else ())

    def flat_fn(*leaves):
        return fn(*jax.tree_util.tree_unflatten(treedef, leaves))

    closed = jax.make_jaxpr(flat_fn)(*flat)
    hits = []
    if args is not None:
        try:
            _interp_jaxpr(closed.jaxpr, closed.consts, list(flat), hits)
            return hits, ""
        except Exception as exc:
            hits = []
            note = (f"concrete pass failed ({type(exc).__name__}: "
                    f"{exc}); index maps with scalar operands not "
                    f"evaluated")
            _walk_static(closed.jaxpr, hits, set())
            return hits, note
    _walk_static(closed.jaxpr, hits, set())
    return hits, ""


# ---------------------------------------------------------------------------
# per-kernel facts
# ---------------------------------------------------------------------------

def _block_dims(block_shape):
    """Block shape with Pallas's squeezed-dim sentinel mapped to 1."""
    return tuple(int(d) if isinstance(d, (int, np.integer)) else 1
                 for d in block_shape)


def _block_bytes(block_shape, dtype):
    n = 1
    for d in _block_dims(block_shape):
        n *= d
    return n * np.dtype(dtype).itemsize


def _scratch_bytes(eqn):
    """Declared scratch bytes: the kernel jaxpr's trailing refs."""
    gm = eqn.params["grid_mapping"]
    n = int(getattr(gm, "num_scratch_operands", 0))
    if not n:
        return 0
    body = eqn.params["jaxpr"]
    total = 0
    for var in body.invars[len(body.invars) - n:]:
        aval = var.aval
        shape = getattr(aval, "shape", ())
        dtype = getattr(aval, "dtype", np.float32)
        size = 1
        for d in shape:
            size *= int(d)
        total += size * np.dtype(dtype).itemsize
    return total


def _tiling_lint(name, block, array):
    """Misaligned / sublane-wasting block dims (see module docstring
    for the exemptions)."""
    out = []
    bdims = _block_dims(block.block_shape)
    adims = tuple(int(d) for d in block.array_shape)
    if len(bdims) < 2:
        return out
    sub = sublane_tile(block.dtype)
    lane_b, lane_a = bdims[-1], adims[-1]
    if lane_b % LANE and lane_b != lane_a:
        out.append({"operand": name, "axis": "lane",
                    "block_dim": lane_b, "tile": LANE,
                    "array_dim": lane_a, "dtype": block.dtype})
    sub_b, sub_a = bdims[-2], adims[-2]
    if sub_b > 1 and sub_b % sub and sub_b != sub_a:
        out.append({"operand": name, "axis": "sublane",
                    "block_dim": sub_b, "tile": sub,
                    "array_dim": sub_a, "dtype": block.dtype})
    return out


@dataclasses.dataclass
class _Block:
    """One BlockMapping, flattened to plain data."""
    block_shape: tuple
    array_shape: tuple
    dtype: str
    index_map: object            # ClosedJaxpr | None


def _block_of(bm):
    sd = bm.array_shape_dtype
    return _Block(block_shape=tuple(bm.block_shape),
                  array_shape=tuple(int(d) for d in sd.shape),
                  dtype=str(np.dtype(sd.dtype)),
                  index_map=getattr(bm, "index_map_jaxpr", None))


def _eval_index_map(index_map, grid, scalar_vals, rank):
    """Block index tuples over the full grid, in Pallas's iteration
    order (row-major, last grid dim fastest): an int array
    ``[n_points, rank]``. Scalar-prefetch refs in the map are
    discharged to plain array reads fed with the captured values."""
    import jax
    import jax.numpy as jnp
    from jax._src.state import discharge as state_discharge

    discharged, dconsts = state_discharge.discharge_state(
        index_map.jaxpr, index_map.consts)
    f = jax.core.jaxpr_as_fun(jax.core.ClosedJaxpr(discharged, dconsts))
    n = int(np.prod(grid))
    idx = np.unravel_index(np.arange(n), grid)   # C order = last fastest

    def one(*gi):
        outs = f(*gi, *scalar_vals)
        return tuple(outs[:rank])

    cols = jax.vmap(one)(*[jnp.asarray(ix, jnp.int32) for ix in idx])
    return np.stack([np.asarray(c) for c in cols], axis=1)


def _fetch_stats(blocks):
    """(distinct, dma_fetches) over row-major grid order. A fetch is
    elided when the block equals the immediately preceding step's."""
    distinct = len({tuple(b) for b in blocks})
    dma = 1
    for i in range(1, len(blocks)):
        if tuple(blocks[i]) != tuple(blocks[i - 1]):
            dma += 1
    return distinct, dma


def _race_scan(blocks):
    """Non-consecutive output-block revisits: ``[{block, steps}]``."""
    last_seen = {}
    flagged = {}
    for i, b in enumerate(map(tuple, blocks)):
        prev = last_seen.get(b)
        if prev is not None and prev != i - 1:
            rec = flagged.setdefault(b, {"block": list(b), "steps": []})
            if prev not in rec["steps"]:
                rec["steps"].append(prev)
            rec["steps"].append(i)
        last_seen[b] = i
    return list(flagged.values())


def kernel_facts(eqn, invals=None, grid_point_cap=DEFAULT_GRID_POINT_CAP):
    """:class:`KernelFacts` for one captured pallas_call equation."""
    gm = eqn.params["grid_mapping"]
    grid = tuple(int(g) for g in gm.grid)
    n_scalars = int(getattr(gm, "num_index_operands", 0))
    n_in = int(gm.num_inputs)
    n_out = int(gm.num_outputs)
    name = getattr(eqn.params.get("name_and_src_info"), "name", None) \
        or "pallas_kernel"
    scalar_vals = None
    if invals is not None:
        scalar_vals = [np.asarray(v) for v in invals[:n_scalars]]
    elif n_scalars == 0:
        scalar_vals = []

    notes = []
    n_points = int(np.prod(grid)) if grid else 1
    sweep = n_points <= grid_point_cap
    if not sweep:
        notes.append(f"grid has {n_points} points (> cap "
                     f"{grid_point_cap}); index maps not evaluated")

    operands, tiling, races = [], [], []
    block_bytes_total = dense_total = dma_total = 0
    mappings = list(gm.block_mappings)
    for i, bm in enumerate(mappings):
        kind = "input" if i < n_in else "output"
        opname = f"in{i}" if i < n_in else f"out{i - n_in}"
        block = _block_of(bm)
        bbytes = _block_bytes(block.block_shape, block.dtype)
        block_bytes_total += bbytes
        tiling.extend(_tiling_lint(opname, block, block))
        distinct = dma = n_points
        evaluated = False
        if sweep and block.index_map is not None and scalar_vals is not None:
            try:
                blocks = _eval_index_map(
                    block.index_map, grid, scalar_vals,
                    len(block.block_shape))
                distinct, dma = _fetch_stats(blocks)
                evaluated = True
                if kind == "output":
                    for rec in _race_scan(blocks):
                        rec["operand"] = opname
                        races.append(rec)
            except Exception as exc:
                notes.append(f"{opname}: index map evaluation failed "
                             f"({type(exc).__name__}: {exc})")
        elif block.index_map is not None and scalar_vals is None:
            notes.append(f"{opname}: index map reads scalar-prefetch "
                         f"operands but no concrete values were "
                         f"captured")
        dense_total += n_points * bbytes
        dma_total += dma * bbytes
        operands.append(OperandFacts(
            name=opname, kind=kind,
            block_shape=_block_dims(block.block_shape),
            array_shape=block.array_shape, dtype=block.dtype,
            block_bytes=bbytes, total_fetches=n_points,
            distinct_blocks=distinct, dma_fetches=dma,
            elided_fraction=round(1.0 - dma / n_points, 6)
            if n_points else 0.0,
            index_map_evaluated=evaluated))

    scratch = _scratch_bytes(eqn)
    return KernelFacts(
        name=name, grid=grid, operands=operands, scratch_bytes=scratch,
        block_bytes_per_step=block_bytes_total,
        vmem_bytes=DOUBLE_BUFFER * block_bytes_total + scratch,
        dense_bytes=dense_total, dma_bytes=dma_total,
        races=races, tiling=tiling, notes=notes)


def _tiling_lint_block(bdims, adims, dtype):
    """Lint arbitrary (block, array, dtype) dims — test seam."""
    blk = _Block(block_shape=bdims, array_shape=adims,
                 dtype=str(np.dtype(dtype)), index_map=None)
    return _tiling_lint("block", blk, blk)


# keep _tiling_lint's signature simple for kernel_facts: it takes the
# operand name and the same _Block twice (block + array live together)
def analyze_kernels(fn, args=None, *, platform="tpu_v5e",
                    grid_point_cap=DEFAULT_GRID_POINT_CAP):
    """Extract and analyze every pallas_call in ``fn`` at ``args``.

    ``fn`` may be jitted or plain; ``args`` concrete arrays (their
    values feed the scalar-prefetch index maps — pass the live call
    args for a DMA-elision proof) or None for a purely structural
    sweep. ``platform`` picks the VMEM budget row from
    `cost.PLATFORMS`. Returns a :class:`KernelAnalysis`.
    """
    t0 = time.perf_counter()
    p = resolve_platform(platform)
    hits, note = extract_pallas_calls(fn, args)
    notes = [note] if note else []
    kernels = []
    seen_names = {}
    for eqn, invals in hits:
        facts = kernel_facts(eqn, invals, grid_point_cap=grid_point_cap)
        n = seen_names.get(facts.name, 0)
        seen_names[facts.name] = n + 1
        if n:
            facts.name = f"{facts.name}#{n}"
        kernels.append(facts)
    return KernelAnalysis(
        kernels=kernels, platform=p.name,
        vmem_budget_bytes=p.vmem_bytes,
        wall_s=time.perf_counter() - t0, notes=notes)


# ---------------------------------------------------------------------------
# elision expectations (the audit's decode proof)
# ---------------------------------------------------------------------------

def ring_dead_block_fraction(positions, max_seq, block_k):
    """The fraction of KV-block grid steps past the rows' occupancy —
    what the flash-decode clamp must elide. Heads multiply live and
    total blocks alike, so the per-row fraction is the per-(row, head)
    fraction."""
    n_kb = max(1, int(max_seq) // int(block_k))
    rows = [int(p) for p in np.asarray(positions).reshape(-1)]
    if not rows:
        return 0.0
    live = sum(min(p // int(block_k) + 1, n_kb) for p in rows)
    return 1.0 - live / (len(rows) * n_kb)
