"""`ds_tpu_tune`: config autotuner over the audit's exact-aval lowering.

DeepCompile's thesis (arxiv 2504.09983) is that the profile->transform
loop should be automatic. This module closes that loop for the discrete
config space the repo already exposes: every candidate is compiled
through the SAME lowering path the audit uses (`audit_engine` — exact
avals, full rule catalog), scored with the roofline cost model
(`analysis/cost.py`), and unsafe candidates are *rejected with a typed
reason*, never scored:

- ``candidate_build_error`` — the engine refused the config or the
  compile threw,
- ``audit_rule_findings`` — error-severity rule findings (donation
  regressions, dtype leaks, host transfers, ...),
- ``peak_memory_over_budget`` — the cost model's static-peak gate
  (`cost.REJECT_PEAK_MEMORY`).

Search strategy is greedy coordinate descent over named dimensions
(:func:`default_dimensions`): sweep one dimension at a time, keep the
best point so far, move on. That bounds compiles to the SUM of the
dimension sizes instead of their product — every compile is wall-clock
the tuner itself pays (the reason ``scan_layers`` exists), so the
default space stays ~15 candidates. A candidate only replaces the
incumbent when its score is STRICTLY lower, so ties keep the user's
base config.

Dimensions over the engine config: ZeRO stage {1,2,3} x
``gather_chunks``, fp8 wire+matmul on/off, ``tensor_parallel.overlap``
chunks/bidirectional, micro x accum via `solve_elastic_batch`. Two
model-side dimensions (remat policy, ``scan_layers``) apply to the toy
GPT-2 the CLI builds — they ride the report's ``model`` section rather
than the engine config JSON.

``--serving`` swaps the space and the evaluator: the dimensions become
the paged KV cache's knobs (``inference.page_size`` multiples of the
prefill chunk, ``inference.host_park_threshold``), and each candidate
compiles through `audit_decode`'s allocator-churn stream instead of a
train step — so a page size that breaks the 2-compile contract, lowers
a host transfer, or fails the engine's divisibility checks is rejected
with a typed reason, and the survivors are scored on the decode
program's roofline cost.

Outputs: the tuned config JSON (``--output``) and an expected-vs-
measured telemetry log (``--expected-log``) — synthetic ``compile`` +
``step`` events in the `ds-tpu-telemetry/1` schema carrying the
winner's predicted step seconds, so ``ds_tpu_metrics diff expected.jsonl
measured.jsonl`` quantifies the model's error once the TPU run exists.
"""

import argparse
import copy
import dataclasses
import json
import math
import os
import sys
import time
from typing import Optional

from deepspeed_tpu.analysis.cost import (PLATFORMS, REJECT_PEAK_MEMORY,
                                         estimate_step_cost,
                                         resolve_platform)

__all__ = ["REJECT_BUILD_ERROR", "REJECT_RULE_FINDINGS",
           "REJECT_PEAK_MEMORY", "Choice", "CandidateResult",
           "TuneResult", "deep_merge", "default_dimensions",
           "serving_dimensions", "build_toy_gpt2_engine",
           "evaluate_candidate", "evaluate_serving_candidate", "tune",
           "expected_events", "write_expected_log", "main"]

# Typed rejection reasons (cost.py owns REJECT_PEAK_MEMORY).
REJECT_BUILD_ERROR = "candidate_build_error"
REJECT_RULE_FINDINGS = "audit_rule_findings"

DIMENSION_NAMES = ("zero", "fp8", "overlap", "batch", "remat", "scan")
SERVING_DIMENSION_NAMES = ("page", "chunk", "batch", "park", "block")


def deep_merge(base, overrides):
    """Recursive dict merge returning a new dict (overrides win)."""
    out = copy.deepcopy(base)
    for key, val in overrides.items():
        if isinstance(val, dict) and isinstance(out.get(key), dict):
            out[key] = deep_merge(out[key], val)
        else:
            out[key] = copy.deepcopy(val)
    return out


@dataclasses.dataclass(frozen=True)
class Choice:
    """One point along a dimension: engine-config overrides plus
    model-side overrides (toy GPT-2 constructor kwargs)."""
    label: str
    config: dict = dataclasses.field(default_factory=dict)
    model: dict = dataclasses.field(default_factory=dict)


def default_dimensions(base_config, world_size=1):
    """The stock search space: ``[(dimension_name, [Choice, ...])]``.

    Every dimension includes the "leave it alone" point implicitly (the
    incumbent is always a candidate), so choices here are pure
    overrides of the current best config.
    """
    from deepspeed_tpu.runtime.elastic.batch import solve_elastic_batch

    zero = [
        Choice("zero1", {"zero_optimization": {"stage": 1}}),
        Choice("zero2", {"zero_optimization": {"stage": 2}}),
        Choice("zero3_gather2",
               {"zero_optimization": {"stage": 3, "gather_chunks": 2}}),
        Choice("zero3_gather4",
               {"zero_optimization": {"stage": 3, "gather_chunks": 4}}),
    ]
    fp8 = [
        Choice("fp8_wire_matmul",
               {"fp8": {"enabled": True,
                        "wire": {"enabled": True,
                                 "dtype": "f8e4m3fn"}}}),
    ]
    overlap = [
        Choice("overlap_off",
               {"tensor_parallel": {"overlap": {"enabled": False}}}),
        Choice("overlap_chunks2",
               {"tensor_parallel": {"overlap": {"enabled": True,
                                                "chunks": 2}}}),
        Choice("overlap_chunks4",
               {"tensor_parallel": {"overlap": {"enabled": True,
                                                "chunks": 4}}}),
        Choice("overlap_chunks4_bidir",
               {"tensor_parallel": {"overlap": {"enabled": True,
                                                "chunks": 4,
                                                "bidirectional": True}}}),
    ]
    batch = []
    target = int(base_config.get("train_batch_size", 8))
    seen = set()
    for accum in (1, 2, 4):
        try:
            plan = solve_elastic_batch(target, world_size,
                                       prefer_accum=accum)
        except Exception:
            continue
        key = (plan.micro_batch, plan.grad_accum)
        if key in seen or not plan.exact:
            continue
        seen.add(key)
        batch.append(Choice(
            f"micro{plan.micro_batch}_accum{plan.grad_accum}",
            {"train_batch_size": plan.global_batch,
             "train_micro_batch_size_per_gpu": plan.micro_batch,
             "gradient_accumulation_steps": plan.grad_accum}))
    remat = [
        Choice("remat_off", model={"remat": False}),
        Choice("remat_dots", model={"remat": True,
                                    "remat_policy": "dots"}),
        Choice("remat_full", model={"remat": True,
                                    "remat_policy": "full"}),
    ]
    scan = [
        Choice("scan_layers", model={"scan_layers": True}),
    ]
    dims = [("zero", zero), ("fp8", fp8), ("overlap", overlap),
            ("batch", batch), ("remat", remat), ("scan", scan)]
    return [(name, choices) for name, choices in dims if choices]


def serving_dimensions(base_config):
    """The ``--serving`` search space: paged-KV knobs over the config's
    ``inference`` block.

    ``page`` sweeps page_size as multiples of the prefill chunk (only
    multiples can keep prefill chunk-aligned; a size that doesn't also
    divide the largest seq bucket is still offered — the engine rejects
    it at build and the tuner reports the typed rejection instead of
    silently skipping the point). ``park`` sweeps the host-RAM
    evacuation threshold: 0 never parks to host, higher values trade
    host-copy wall for device pages under session churn. ``block``
    sweeps the flash-decode ``attention_block_k`` (the engine clamps it
    to the page size and rejects non-divisors at build, so an
    incompatible pairing comes back as a typed rejection): smaller
    blocks elide dead-cache DMAs at finer granularity — visible to the
    score only because `evaluate_serving_candidate` prices kernel HBM
    traffic from the analyzer's elision-aware DMA bytes.

    ``chunk`` sweeps ``prefill_chunk`` — the disaggregated prefill
    tier's unit of work AND the page-size alignment quantum, so a
    chunk that no longer divides the candidate's page size (or exceeds
    a bucket) is engine-rejected and surfaces as a typed
    ``candidate_build_error``, never a silent skip. ``batch`` sweeps
    decode ``max_batch``: more concurrent rows amortize weight
    streaming per token but multiply the KV pool pressure; with
    disaggregated tiers (ISSUE 20) these two dimensions are exactly
    the per-tier sizing knobs (``prefill_chunk`` for the prefill tier,
    ``max_batch`` for the decode tier).
    """
    inf = base_config.get("inference") or {}
    pc = int(inf.get("prefill_chunk", 4))
    buckets = inf.get("seq_buckets") or (16, 32)
    max_seq = max(int(b) for b in buckets)
    page = [Choice(f"page{pc * mult}",
                   {"inference": {"page_size": pc * mult}})
            for mult in (1, 2, 4) if pc * mult <= max_seq]
    chunk = [Choice(f"chunk{c}",
                    {"inference": {"prefill_chunk": c}})
             for c in (2, 4, 8) if c <= max_seq]
    batch = [Choice(f"batch{b}",
                    {"inference": {"max_batch": b}})
             for b in (1, 2, 4)]
    park = [Choice(f"park{int(t * 100)}",
                   {"inference": {"host_park_threshold": t}})
            for t in (0.0, 0.25, 0.5)]
    block = [Choice(f"blk{bk}",
                    {"inference": {"attention_block_k": bk}})
             for bk in (2, 4, 8) if bk <= max_seq]
    dims = [("page", page), ("chunk", chunk), ("batch", batch),
            ("park", park), ("block", block)]
    return [(name, choices) for name, choices in dims if choices]


# ---------------------------------------------------------------------------
# candidate evaluation (build -> audit -> cost)
# ---------------------------------------------------------------------------

def build_toy_gpt2_engine(config, model_overrides=None):
    """``(engine, batch)`` for one candidate: toy GPT-2 supplies the
    model/loss (the ``ds_tpu_audit --config`` convention); the tuner's
    model-side knobs (``remat``/``remat_policy``/``scan_layers``) are
    `GPT2Config` kwargs."""
    import jax
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (GPT2LMHead, gpt2_tiny,
                                           init_gpt2_params,
                                           make_gpt2_loss_fn)

    model = GPT2LMHead(gpt2_tiny(**(model_overrides or {})))
    params = init_gpt2_params(model, jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=copy.deepcopy(config),
        loss_fn=make_gpt2_loss_fn(model), params=params)
    rows = int(config.get("train_batch_size", 8))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 255, (rows, 32)).astype(np.int32)}
    return engine, batch


def _batch_tokens(batch):
    for leaf in batch.values():
        size = getattr(leaf, "size", None)
        if size:
            return int(size)
    return 0


@dataclasses.dataclass
class CandidateResult:
    label: str
    dimension: str
    config: dict
    model: dict
    reject_reason: Optional[str] = None
    reject_detail: str = ""
    flavor: str = ""
    findings: int = 0
    tokens: int = 0
    cost: object = None              # cost.StepCost when scored
    collective_bytes_by_dtype: dict = dataclasses.field(
        default_factory=dict)
    audit_wall_s: float = 0.0

    @property
    def score(self):
        if self.reject_reason or self.cost is None:
            return math.inf
        return self.cost.score

    def to_dict(self):
        return {
            "label": self.label,
            "dimension": self.dimension,
            "config": self.config,
            "model": self.model,
            "ok": self.reject_reason is None,
            "reject_reason": self.reject_reason,
            "reject_detail": self.reject_detail,
            "flavor": self.flavor,
            "findings": self.findings,
            "score": None if math.isinf(self.score) else self.score,
            "cost": self.cost.to_dict() if self.cost is not None else None,
            "audit_wall_s": self.audit_wall_s,
        }


def evaluate_candidate(config, model_overrides, *, build=None,
                       platform="tpu_v5e", peak_budget_bytes=None,
                       rules=None, label="candidate", dimension="base"):
    """Compile one candidate through the audit path and score it.

    Never raises for a bad candidate: build/compile failures and
    error-severity rule findings come back as typed rejections so the
    search can report *why* a point dropped out.
    """
    import jax
    from deepspeed_tpu.analysis.audit import audit_engine
    from deepspeed_tpu.analysis.rules import SEV_ERROR

    build = build or build_toy_gpt2_engine
    res = CandidateResult(label=label, dimension=dimension,
                          config=config, model=dict(model_overrides or {}))
    t0 = time.perf_counter()
    try:
        engine, batch = build(config, model_overrides)
        report = audit_engine(engine, batch, rules=rules)
    except Exception as exc:
        res.reject_reason = REJECT_BUILD_ERROR
        res.reject_detail = f"{type(exc).__name__}: {exc}"
        res.audit_wall_s = round(time.perf_counter() - t0, 3)
        return res
    res.audit_wall_s = round(time.perf_counter() - t0, 3)
    res.flavor = report.flavor
    res.findings = len(report.findings)
    res.tokens = _batch_tokens(batch)
    res.collective_bytes_by_dtype = \
        report.stats.get("collective_bytes_by_dtype") or {}
    errors = [f for f in report.findings if f.severity == SEV_ERROR]
    if errors:
        res.reject_reason = REJECT_RULE_FINDINGS
        res.reject_detail = "; ".join(
            f"{f.rule}: {f.message}" for f in errors[:4])
        return res
    sites = (report.stats.get("jaxpr") or {}).get("collective_sites") or []
    n_devices = getattr(engine.mesh, "size", None) or jax.device_count()
    cost = estimate_step_cost(
        report.hlo_text, n_devices=n_devices, platform=platform,
        collective_sites=sites, peak_budget_bytes=peak_budget_bytes)
    res.cost = cost
    if cost.reject_reason:
        res.reject_reason = cost.reject_reason
        res.reject_detail = (
            f"static peak {cost.peak_bytes} B > budget "
            f"{cost.peak_budget_bytes} B")
    return res


def evaluate_serving_candidate(config, model_overrides=None, *,
                               build=None, platform="tpu_v5e",
                               peak_budget_bytes=None, rules=None,
                               label="candidate", dimension="base"):
    """Compile one paged-serving candidate through ``audit_decode``'s
    allocator-churn stream and score its decode program.

    The candidate's ``inference`` block configures the engine
    (``kv_layout`` forced to "paged" — this mode tunes the paged
    knobs); the full rule catalog runs over the post-churn decode HLO,
    so a page_size that breaks the 2-compile contract or lowers a host
    transfer comes back as a typed rejection, never a score. The audit
    runs with ``kernels=True``, so the score includes the decode
    kernel's HBM time priced from the analyzer's elision-aware DMA
    bytes — which is what lets the ``block`` dimension rank
    ``attention_block_k`` on real traffic (dense operand sizes are
    identical across block sizes). Drop-in for
    :func:`evaluate_candidate` in the greedy driver
    (``model_overrides``/``build`` are accepted and ignored).
    """
    from deepspeed_tpu.analysis.audit import audit_decode
    from deepspeed_tpu.analysis.rules import SEV_ERROR

    inf = dict(config.get("inference") or {})
    inf.pop("kv_layout", None)
    res = CandidateResult(label=label, dimension=dimension,
                          config=config, model={})
    t0 = time.perf_counter()
    try:
        report = audit_decode(config_overrides=inf, rules=rules,
                              kv_layout="paged", kernels=True)
    except Exception as exc:
        res.reject_reason = REJECT_BUILD_ERROR
        res.reject_detail = f"{type(exc).__name__}: {exc}"
        res.audit_wall_s = round(time.perf_counter() - t0, 3)
        return res
    res.audit_wall_s = round(time.perf_counter() - t0, 3)
    res.flavor = report.flavor
    res.findings = len(report.findings)
    # one decode step produces max_batch tokens — the serving analog of
    # the train step's batch tokens for the cost model's per-token view
    res.tokens = int((report.stats.get("cache") or {}).get(
        "max_batch", 0))
    res.collective_bytes_by_dtype = \
        report.stats.get("collective_bytes_by_dtype") or {}
    errors = [f for f in report.findings if f.severity == SEV_ERROR]
    if errors:
        res.reject_reason = REJECT_RULE_FINDINGS
        res.reject_detail = "; ".join(
            f"{f.rule}: {f.message}" for f in errors[:4])
        return res
    kstats = report.stats.get("kernels") or {}
    kernel_facts = [
        {"name": name, "dma_bytes": kd.get("dma_bytes", 0),
         "dense_bytes": kd.get("dense_bytes", 0)}
        for name, kd in (kstats.get("kernels") or {}).items()]
    cost = estimate_step_cost(
        report.hlo_text, n_devices=1, platform=platform,
        peak_budget_bytes=peak_budget_bytes,
        kernel_facts=kernel_facts)
    res.cost = cost
    if cost.reject_reason:
        res.reject_reason = cost.reject_reason
        res.reject_detail = (
            f"static peak {cost.peak_bytes} B > budget "
            f"{cost.peak_budget_bytes} B")
    return res


# ---------------------------------------------------------------------------
# the greedy search driver
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TuneResult:
    platform: str
    base: CandidateResult
    best: CandidateResult
    candidates: list
    skipped: int = 0

    @property
    def improved(self):
        """True when the winner STRICTLY beats the untuned base."""
        return self.best.score < self.base.score

    @property
    def tuned_config(self):
        return self.best.config

    @property
    def model_overrides(self):
        return self.best.model

    def to_dict(self):
        return {
            "platform": self.platform,
            "improved": self.improved,
            "base": self.base.to_dict(),
            "best": self.best.to_dict(),
            "tuned_config": self.tuned_config,
            "model_overrides": self.model_overrides,
            "candidates": [c.to_dict() for c in self.candidates],
            "candidates_total": len(self.candidates),
            "skipped": self.skipped,
        }


def tune(base_config, *, build=None, dimensions=None, platform="tpu_v5e",
         peak_budget_bytes=None, rules=None, max_candidates=0, log=None,
         evaluate=None):
    """Greedy coordinate-descent search (see module docstring).

    ``dimensions`` defaults to :func:`default_dimensions`;
    ``max_candidates`` (0 = unbounded) caps compiles after the base.
    ``evaluate`` swaps the candidate evaluator (default
    :func:`evaluate_candidate`; ``--serving`` passes
    :func:`evaluate_serving_candidate`). Returns a :class:`TuneResult`;
    the base config itself is always the first candidate, so
    ``result.improved`` compares against it.
    """
    import jax

    platform = resolve_platform(platform)
    say = log or (lambda msg: None)
    evaluate = evaluate or evaluate_candidate
    if dimensions is None:
        dimensions = default_dimensions(base_config, jax.device_count())

    say(f"tune: base config on platform {platform.name}")
    base = evaluate(
        base_config, {}, build=build, platform=platform,
        peak_budget_bytes=peak_budget_bytes, rules=rules,
        label="base", dimension="base")
    results = [base]
    best = base
    seen = {json.dumps([base_config, {}], sort_keys=True)}
    skipped = 0
    for dim_name, choices in dimensions:
        for choice in choices:
            cand_cfg = deep_merge(best.config, choice.config)
            cand_model = {**best.model, **choice.model}
            key = json.dumps([cand_cfg, cand_model], sort_keys=True)
            if key in seen:
                continue
            if max_candidates and len(results) > max_candidates:
                skipped += 1
                continue
            seen.add(key)
            res = evaluate(
                cand_cfg, cand_model, build=build, platform=platform,
                peak_budget_bytes=peak_budget_bytes, rules=rules,
                label=choice.label, dimension=dim_name)
            results.append(res)
            if res.reject_reason:
                say(f"tune: [{dim_name}] {choice.label} rejected "
                    f"({res.reject_reason})")
            else:
                say(f"tune: [{dim_name}] {choice.label} score "
                    f"{res.score * 1e6:.2f}us")
            if res.score < best.score:
                best = res
                say(f"tune: [{dim_name}] {choice.label} is the new best")
    if skipped:
        say(f"tune: --max-candidates dropped {skipped} candidate(s) "
            "unevaluated")
    return TuneResult(platform=platform.name, base=base, best=best,
                      candidates=results, skipped=skipped)


# ---------------------------------------------------------------------------
# expected-vs-measured report (ds_tpu_metrics diff-compatible)
# ---------------------------------------------------------------------------

def expected_events(result, steps=8):
    """Synthetic telemetry events predicting the winner's run: one
    ``compile`` event with the static facts + ``steps`` identical
    ``step`` events at the predicted wall. Schema `ds-tpu-telemetry/1`,
    so ``ds_tpu_metrics diff expected.jsonl measured.jsonl`` reports
    prediction error directly. (Phase names here are the cost model's
    compute/interconnect split, not the runtime's span names — the
    step-time rows are the comparable ones.)"""
    from deepspeed_tpu.telemetry.events import SCHEMA_VERSION

    best = result.best
    cost = best.cost
    if cost is None:
        return []
    now = time.time()
    tokens = best.tokens
    fpt = (cost.flops / tokens) if tokens else 0
    events = [{
        "schema": SCHEMA_VERSION, "event": "run_start", "t": now,
        "source": "ds_tpu_tune", "flavor": best.flavor,
        "platform": result.platform,
    }, {
        "schema": SCHEMA_VERSION, "event": "compile", "t": now,
        "source": "ds_tpu_tune", "flavor": best.flavor,
        "flops_per_token": fpt,
        "batch_tokens": tokens,
        "collective_bytes_by_dtype": best.collective_bytes_by_dtype,
        "static_peak_bytes": cost.peak_bytes,
        "expected_step_s": cost.step_seconds,
        "kernel_dma_bytes": cost.kernel_dma_bytes,
        "kernel_dense_bytes": cost.kernel_dense_bytes,
    }]
    for i in range(steps):
        events.append({
            "schema": SCHEMA_VERSION, "event": "step", "t": now,
            "source": "ds_tpu_tune", "flavor": best.flavor,
            "step": i, "wall_s": cost.step_seconds, "tokens": tokens,
            "phases": {
                "compute": cost.compute_seconds,
                "interconnect": cost.exposed_interconnect_seconds,
            },
        })
    return events


def write_expected_log(path, result, steps=8):
    events = expected_events(result, steps=steps)
    with open(path, "w") as f:
        for evt in events:
            f.write(json.dumps(evt, sort_keys=True) + "\n")
    return len(events)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _format_text(result):
    lines = [f"tune: platform {result.platform}, "
             f"{len(result.candidates)} candidate(s) compiled through "
             "the audit path"]
    head = (f"{'candidate':28s}{'dim':10s}{'score_us':>10s}"
            f"{'wire_MB':>9s}{'peak_MB':>9s}  status")
    lines.append(head)
    for res in result.candidates:
        if res.cost is not None:
            score = "inf" if math.isinf(res.score) \
                else f"{res.score * 1e6:.2f}"
            wire = f"{res.cost.wire_bytes / (1 << 20):.2f}"
            peak = f"{res.cost.peak_bytes / (1 << 20):.2f}"
        else:
            score = wire = peak = "-"
        status = res.reject_reason or (
            "best" if res is result.best else "ok")
        lines.append(f"{res.label:28s}{res.dimension:10s}{score:>10s}"
                     f"{wire:>9s}{peak:>9s}  {status}")
    if result.improved:
        gain = (1.0 - result.best.score / result.base.score) * 100.0
        lines.append(
            f"winner: {result.best.label} — score "
            f"{result.best.score * 1e6:.2f}us, "
            f"{gain:.1f}% below the base config "
            f"({result.base.score * 1e6:.2f}us), "
            f"{result.best.findings} rule finding(s)")
    else:
        lines.append("winner: base config (no candidate strictly "
                     "improved the cost-model score)")
    if result.best.model:
        lines.append(f"model overrides (apply to the model ctor): "
                     f"{json.dumps(result.best.model, sort_keys=True)}")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_tpu_tune",
        description="Search overlap/fp8/ZeRO/batch/remat/scan config "
                    "space: compile each candidate through the audit's "
                    "exact-aval lowering (rule findings reject it), "
                    "score with the roofline cost model, emit the tuned "
                    "config.")
    parser.add_argument("--config", required=True,
                        help="base DeepSpeed-style JSON config (the "
                             "untuned default being beaten)")
    parser.add_argument("--platform", default=None,
                        help="cost-model constants table to use "
                             f"(known: {sorted(PLATFORMS)}; default: "
                             "the config's analysis.platform, else "
                             "tpu_v5e)")
    parser.add_argument("--serving", action="store_true",
                        help="tune the paged serving engine instead of "
                             "the train step: sweep inference.page_size "
                             "and host_park_threshold, each candidate "
                             "compiled through the ds_tpu_audit decode "
                             "churn stream (contract-breaking configs "
                             "are rejected, not scored)")
    parser.add_argument("--dimensions", default=None,
                        help="comma-separated subset of the search "
                             f"dimensions (default: all of "
                             f"{list(DIMENSION_NAMES)}; with --serving: "
                             f"{list(SERVING_DIMENSION_NAMES)})")
    parser.add_argument("--peak-budget-mb", type=float, default=None,
                        help="reject candidates whose static peak "
                             "exceeds this budget (default: "
                             "analysis.peak_memory_budget_mb from the "
                             "config, if set)")
    parser.add_argument("--max-candidates", type=int, default=0,
                        help="cap on candidate compiles after the base "
                             "(0 = unbounded)")
    parser.add_argument("--output", default=None, metavar="FILE",
                        help="write the tuned config JSON here")
    parser.add_argument("--expected-log", default=None, metavar="FILE",
                        help="write the ds_tpu_metrics-compatible "
                             "expected-run JSONL here")
    parser.add_argument("--metrics-steps", type=int, default=8,
                        help="synthetic step events in --expected-log "
                             "(default 8)")
    parser.add_argument("--compilation-cache-dir", default=None,
                        metavar="DIR",
                        help="persistent XLA compile cache for every "
                             "candidate (reruns become cache hits)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the full JSON report instead of text")
    args = parser.parse_args(argv)

    # Candidate compiles read compile-time artifacts; default to the CPU
    # backend with an 8-device virtual mesh (the ds_tpu_audit setup) so
    # tuning runs anywhere. Must happen before jax import.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "cpu" in os.environ.get("JAX_PLATFORMS", "") \
            and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()

    try:
        with open(args.config) as f:
            base_config = json.load(f)
    except (OSError, ValueError) as exc:
        parser.error(f"cannot read --config: {exc}")
    if not isinstance(base_config, dict):
        parser.error("--config must hold a JSON object")

    platform_name = args.platform or \
        (base_config.get("analysis") or {}).get("platform") or "tpu_v5e"
    try:
        platform = resolve_platform(platform_name)
    except ValueError as exc:
        parser.error(str(exc))

    import jax

    if args.compilation_cache_dir:
        base_config = deep_merge(
            base_config,
            {"compilation_cache_dir": args.compilation_cache_dir})
        # toy candidates compile in well under the persistence
        # threshold; cache them anyway so tuner reruns are hits
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)

    known_names = SERVING_DIMENSION_NAMES if args.serving \
        else DIMENSION_NAMES
    if args.serving:
        dimensions = serving_dimensions(base_config)
    else:
        dimensions = None
    if args.dimensions:
        wanted = [d.strip() for d in args.dimensions.split(",")
                  if d.strip()]
        unknown = sorted(set(wanted) - set(known_names))
        if unknown:
            parser.error(f"unknown dimension(s) {unknown}; known: "
                         f"{list(known_names)}")
        stock = dict(serving_dimensions(base_config)) if args.serving \
            else dict(default_dimensions(base_config,
                                         jax.device_count()))
        dimensions = [(name, stock[name]) for name in wanted
                      if name in stock]

    peak_budget_bytes = None
    if args.peak_budget_mb:
        peak_budget_bytes = int(args.peak_budget_mb * (1 << 20))
    else:
        analysis_cfg = base_config.get("analysis") or {}
        budget_mb = analysis_cfg.get("peak_memory_budget_mb") or 0
        if budget_mb:
            peak_budget_bytes = int(float(budget_mb) * (1 << 20))

    result = tune(base_config, dimensions=dimensions, platform=platform,
                  peak_budget_bytes=peak_budget_bytes,
                  max_candidates=args.max_candidates,
                  log=lambda msg: print(msg, file=sys.stderr),
                  evaluate=evaluate_serving_candidate if args.serving
                  else None)

    if args.output:
        with open(args.output, "w") as f:
            json.dump(result.tuned_config, f, indent=2, sort_keys=True)
            f.write("\n")
    if args.expected_log:
        write_expected_log(args.expected_log, result,
                           steps=args.metrics_steps)

    if args.as_json:
        from deepspeed_tpu.telemetry.events import SCHEMA_VERSION
        print(json.dumps({"schema": SCHEMA_VERSION,
                          **result.to_dict()},
                         indent=2, sort_keys=True))
    else:
        print(_format_text(result))
    # 0: a scoreable winner exists (tuned or base); 1: nothing scored.
    return 0 if not math.isinf(result.best.score) else 1


if __name__ == "__main__":
    sys.exit(main())
