"""Roofline per-step cost model over audited HLO facts.

The audit subsystem (`analysis/audit.py`) lowers a training step with its
exact avals and extracts static facts: per-dtype collective bytes
(`hlo.collective_bytes` / `hlo.ring_send_bytes`), trip-count-aware
collective execution counts (`hlo.collective_counts`), static peak memory
(`hlo.estimate_peak_memory`) and — new here — matmul FLOPs from ``dot``
shapes (:func:`dot_flops`). This module turns those facts into a scalar
per-step time estimate so the autotuner (`analysis/tune.py`) can *rank*
candidate configs without a TPU attached.

The model is a classic alpha-beta roofline, deliberately small:

* compute time = dot FLOPs / peak matmul throughput (MXU-bound; the
  elementwise tail is assumed to hide under the matmuls),
* interconnect time per collective kind = ring send bytes / per-link ICI
  bandwidth + executions x serialized ring hops x per-hop latency,
* overlap credit: ``collective-permute`` traffic belonging to
  `SiteRecord`-registered chunked rings (``chunks > 1`` — the
  collective-matmul / quantized-ring lowerings of `parallel/collectives`)
  interleaves per-chunk sends with per-chunk compute, so only the first
  chunk's ring fill is exposed: its bandwidth AND latency terms are
  divided by the chunk count. This is optimistic by construction (it
  assumes every chunk's compute fully covers the next chunk's sends) —
  fine for *ranking*, which is all the tuner needs; `ds_tpu_metrics diff`
  closes the loop against measured walls when a TPU is present.

Absolute numbers are only as good as the per-platform constants table
(:data:`PLATFORMS` — datasheet-order-of-magnitude, not calibrated);
*ratios* between two candidates lowered the same way are the contract
the tests pin.

Candidates whose static peak exceeds the budget are not scored at all:
:func:`estimate_step_cost` marks them rejected with the typed reason
:data:`REJECT_PEAK_MEMORY` and an infinite score, so the tuner can
surface *why* a point left the search space.
"""

import dataclasses
import math
import re
from typing import Optional

from deepspeed_tpu.analysis import hlo as hlo_lib

# Typed rejection reason: static peak over the configured budget.
REJECT_PEAK_MEMORY = "peak_memory_over_budget"


@dataclasses.dataclass(frozen=True)
class Platform:
    """Roofline constants for one accelerator platform.

    ``flops_per_second`` is dense bf16 matmul throughput;
    ``ici_bytes_per_second`` is per-link, per-direction interconnect
    bandwidth; ``ici_latency_seconds`` is the per-hop launch latency
    (the alpha in alpha-beta); ``hbm_bytes`` is device memory capacity
    (the default peak budget when the config sets none); ``vmem_bytes``
    is the per-core vector-memory budget a single Pallas grid step's
    working set must fit in (`analysis/kernels.py`'s ``kernel_vmem``
    rule checks against it).
    """
    name: str
    flops_per_second: float
    hbm_bytes_per_second: float
    ici_bytes_per_second: float
    ici_latency_seconds: float
    hbm_bytes: int
    vmem_bytes: int = 16 * 2 ** 20


# Datasheet-order constants (see docs/analysis.md). The "cpu" row is a
# deterministic stand-in so ranking tests run anywhere; its VMEM budget
# mirrors tpu_v5e so interpret-mode kernel audits gate like hardware.
PLATFORMS = {
    "tpu_v5e": Platform("tpu_v5e", 197e12, 819e9, 45e9, 1e-6,
                        16 * 2 ** 30, 16 * 2 ** 20),
    "tpu_v5p": Platform("tpu_v5p", 459e12, 2765e9, 100e9, 1e-6,
                        95 * 2 ** 30, 16 * 2 ** 20),
    "tpu_v4": Platform("tpu_v4", 275e12, 1228e9, 50e9, 1e-6,
                       32 * 2 ** 30, 16 * 2 ** 20),
    "cpu": Platform("cpu", 1e12, 100e9, 10e9, 1e-6, 16 * 2 ** 30,
                    16 * 2 ** 20),
}


def resolve_platform(platform):
    """str | Platform -> Platform (ValueError lists the known names)."""
    if isinstance(platform, Platform):
        return platform
    try:
        return PLATFORMS[platform]
    except KeyError:
        raise ValueError(
            f"unknown platform {platform!r}; known: "
            f"{sorted(PLATFORMS)}") from None


# Serialized ring hops one *execution* of each collective pays at group
# size N (latency term; the bandwidth term uses hlo._RING_SEND_FACTORS).
_RING_HOPS = {
    "all-reduce": lambda n: 2 * (n - 1),
    "all-gather": lambda n: n - 1,
    "reduce-scatter": lambda n: n - 1,
    "all-to-all": lambda n: 1,
    "collective-permute": lambda n: 1,
    "collective-broadcast": lambda n: 1,
}


# ---------------------------------------------------------------------------
# FLOPs from dot shapes
# ---------------------------------------------------------------------------

_DOT_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")
_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
                     r"(?P<shape>\S+\[[\d,]*\])")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")


def _dims(shape_text):
    m = hlo_lib._SHAPE_RE.search(shape_text)
    if not m:
        return None
    return [int(d) for d in m.group(2).split(",") if d] if m.group(2) \
        else []


def dot_flops(hlo_text):
    """Total matmul FLOPs of one step, from ``dot`` op shapes.

    Each ``dot`` contributes ``2 * prod(output dims) * prod(lhs
    contracting dim sizes)`` (multiply + add per MAC), weighted by its
    computation's execution multiplier so dots inside ``while``/``scan``
    bodies count once per trip — the same trip-aware accounting as
    `hlo.collective_bytes`. Works on compiled HLO (operand shapes inline
    on the dot line) and on pre-optimization dumps (falls back to the
    operand's definition line within the same computation).
    """
    comps, entry = hlo_lib.split_computations(hlo_text)
    if not comps:
        comps = {"__flat__": hlo_text.splitlines()}
        mults = {"__flat__": 1}
    else:
        mults = hlo_lib.computation_multipliers(hlo_text)
    total = 0
    for cname, lines in comps.items():
        mult = mults.get(cname, 0)
        if not mult:
            continue
        defs = None
        for line in lines:
            if " dot(" not in line:
                continue
            head, _, tail = line.partition(" dot(")
            out_dims = _dims(head.split("=", 1)[-1])
            inner = tail.split(")", 1)[0]
            cm = _DOT_CONTRACT_RE.search(line)
            if out_dims is None or cm is None:
                continue
            contract = [int(d) for d in cm.group(1).split(",") if d]
            lhs_dims = None
            operand_shapes = hlo_lib._SHAPE_RE.findall(inner)
            if operand_shapes:
                dt, dims = operand_shapes[0]
                lhs_dims = [int(d) for d in dims.split(",") if d]
            else:
                # pre-optimization text: look the lhs operand up by name
                if defs is None:
                    defs = {}
                    for dl in lines:
                        dm = _DEF_RE.match(dl)
                        if dm:
                            defs[dm.group("name")] = dm.group("shape")
                names = _OPERAND_NAME_RE.findall(inner)
                if not names:
                    names = [t.strip() for t in inner.split(",")]
                if names and names[0] in defs:
                    lhs_dims = _dims(defs[names[0]])
            if lhs_dims is None:
                continue
            macs = 1
            for d in out_dims:
                macs *= d
            for axis in contract:
                if axis < len(lhs_dims):
                    macs *= lhs_dims[axis]
            total += 2 * macs * mult
    return total


# ---------------------------------------------------------------------------
# the cost estimate
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class StepCost:
    """One candidate's roofline estimate (see module docstring)."""
    platform: str
    n_devices: int
    flops: int
    compute_seconds: float
    wire_bytes: int
    wire_bytes_by_dtype: dict
    interconnect_seconds: float          # fully blocking alpha-beta time
    exposed_interconnect_seconds: float  # after the chunked-ring credit
    overlap_credit_seconds: float
    overlap_chunks: int                  # effective chunk count (1 = none)
    peak_bytes: int
    peak_budget_bytes: Optional[int]
    step_seconds: float                  # compute + exposed + kernel HBM
    kernel_dma_bytes: int = 0            # elision-aware Pallas traffic
    kernel_dense_bytes: int = 0          # every-grid-step-pays baseline
    kernel_hbm_seconds: float = 0.0
    reject_reason: Optional[str] = None

    @property
    def ok(self):
        return self.reject_reason is None

    @property
    def score(self):
        """Ranking key: estimated step seconds (+inf when rejected)."""
        return math.inf if self.reject_reason else self.step_seconds

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["score"] = None if math.isinf(self.score) else self.score
        d["ok"] = self.ok
        return d


def _site_chunks(collective_sites):
    """Effective overlap chunk count from `SiteRecord`s (dataclasses or
    the dict form the audit stats carry): the smallest ``chunks > 1``
    among registered rings — conservative when sites disagree — or 1
    when nothing is chunked."""
    chunked = []
    for rec in collective_sites or ():
        chunks = getattr(rec, "chunks", None)
        if chunks is None and isinstance(rec, dict):
            chunks = rec.get("chunks")
        if chunks and chunks > 1:
            chunked.append(int(chunks))
    return min(chunked) if chunked else 1


def _kernel_traffic_bytes(kernel_facts):
    """(dma, dense) byte totals from kernel-analysis fact dicts (the
    `kernels.KernelAnalysis.kernel_cost_facts` form, or any mapping
    with ``dma_bytes`` / ``dense_bytes``)."""
    dma = dense = 0
    for rec in kernel_facts or ():
        get = rec.get if isinstance(rec, dict) else \
            lambda k, d=0, r=rec: getattr(r, k, d)
        dma += int(get("dma_bytes", 0))
        dense += int(get("dense_bytes", 0))
    return dma, dense


def estimate_step_cost(hlo_text, *, n_devices, platform="tpu_v5e",
                       collective_sites=(), peak_budget_bytes=None,
                       kernel_facts=(), kernel_traffic="dma"):
    """Roofline cost of one compiled step (see module docstring).

    ``collective_sites`` is the trace-time `SiteRecord` list (the audit
    stats' ``jaxpr.collective_sites``); chunked rings there earn the
    overlap credit. ``peak_budget_bytes`` (None = no gate) rejects the
    candidate with :data:`REJECT_PEAK_MEMORY` when the static peak
    exceeds it.

    ``kernel_facts`` carries per-Pallas-kernel traffic from
    `kernels.KernelAnalysis.kernel_cost_facts`; their HBM time is
    added to the step. ``kernel_traffic`` selects which byte count is
    priced: ``"dma"`` (default) uses the elision-aware distinct-block
    DMA bytes the analyzer proved, ``"dense"`` prices every grid step's
    block as if nothing were elided — the pre-analysis assumption, kept
    for A/B-ing what elision-aware ranking changes.
    """
    if kernel_traffic not in ("dma", "dense"):
        raise ValueError(f"kernel_traffic must be 'dma' or 'dense', "
                         f"got {kernel_traffic!r}")
    p = resolve_platform(platform)
    n = max(2, int(n_devices))

    flops = dot_flops(hlo_text)
    compute_s = flops / p.flops_per_second

    sends = hlo_lib.ring_send_bytes(hlo_text, n, by_dtype=True)
    counts = hlo_lib.collective_counts(hlo_text)
    wire_by_dtype = {}
    bw_s = {}
    for op, per_dtype in sends.items():
        if op == "total":
            continue
        for dt, b in per_dtype.items():
            wire_by_dtype[dt] = wire_by_dtype.get(dt, 0) + b
        bw_s[op] = sum(per_dtype.values()) / p.ici_bytes_per_second
    lat_s = {op: counts.get(op, 0) * _RING_HOPS[op](n) *
             p.ici_latency_seconds for op in bw_s}
    blocking_s = sum(bw_s.values()) + sum(lat_s.values())

    chunks = _site_chunks(collective_sites)
    permute_s = bw_s.get("collective-permute", 0.0) + \
        lat_s.get("collective-permute", 0.0)
    credit_s = permute_s * (1.0 - 1.0 / chunks) if chunks > 1 else 0.0
    exposed_s = blocking_s - credit_s

    kdma, kdense = _kernel_traffic_bytes(kernel_facts)
    kernel_bytes = kdma if kernel_traffic == "dma" else kdense
    kernel_hbm_s = kernel_bytes / p.hbm_bytes_per_second

    peak = hlo_lib.estimate_peak_memory(hlo_text)["peak_bytes"]
    reject = None
    if peak_budget_bytes is not None and peak > peak_budget_bytes:
        reject = REJECT_PEAK_MEMORY

    return StepCost(
        platform=p.name,
        n_devices=n,
        flops=flops,
        compute_seconds=compute_s,
        wire_bytes=sends.get("total", 0),
        wire_bytes_by_dtype=wire_by_dtype,
        interconnect_seconds=blocking_s,
        exposed_interconnect_seconds=exposed_s,
        overlap_credit_seconds=credit_s,
        overlap_chunks=chunks,
        peak_bytes=peak,
        peak_budget_bytes=peak_budget_bytes,
        step_seconds=compute_s + exposed_s + kernel_hbm_s,
        kernel_dma_bytes=kdma,
        kernel_dense_bytes=kdense,
        kernel_hbm_seconds=kernel_hbm_s,
        reject_reason=reject,
    )
