"""Compiled-program audit subsystem (see docs/analysis.md).

Static analysis over compiled train steps at two levels. HLO text
(`analysis/hlo.py`): trip-count-aware collective accounting,
donation/aliasing audits, ZeRO byte budgets, dtype hygiene,
host-transfer and recompile detection, and a schedule-order liveness
estimator for static peak memory. Traced jaxpr (`analysis/jaxpr.py`):
collective-deadlock proofs (divergent control flow, unchained
concurrent permutes) and PartitionSpec flow lint (accidental
replication, implicit reshards) — all before the program ever runs.
The declarative rule catalog lives in `analysis/rules.py`, the
orchestrator + stock-flavor builders in `analysis/audit.py`;
``bin/ds_tpu_audit`` fronts it all from the command line.

On top of the facts the audit extracts, `analysis/cost.py` fits a
roofline per-step cost (compute vs interconnect with an overlap credit
for chunked rings) and `analysis/tune.py` (``bin/ds_tpu_tune``)
searches the discrete config space with it — every candidate compiled
through the audit path, unsafe ones rejected with a typed reason.
"""

from deepspeed_tpu.analysis.hlo import (
    aliased_param_numbers,
    collective_bytes,
    collective_ops,
    computation_multipliers,
    estimate_peak_memory,
    host_transfer_ops,
    input_output_aliases,
    ring_send_bytes,
    split_computations,
    while_loops,
)
from deepspeed_tpu.analysis.jaxpr import (
    CollectiveSite,
    ReshardEvent,
    check_divergent_collectives,
    check_unordered_permutes,
    collect_collectives,
    input_specs_of,
    propagate_partition_specs,
    trace_jaxpr,
)
from deepspeed_tpu.analysis.rules import (
    RULE_IDS,
    RULES,
    SEV_ERROR,
    SEV_INFO,
    SEV_WARNING,
    Finding,
    StepContext,
    run_rules,
)
from deepspeed_tpu.analysis.cost import (
    PLATFORMS,
    REJECT_PEAK_MEMORY,
    Platform,
    StepCost,
    dot_flops,
    estimate_step_cost,
    resolve_platform,
)
from deepspeed_tpu.analysis.tune import (
    REJECT_BUILD_ERROR,
    REJECT_RULE_FINDINGS,
    Choice,
    TuneResult,
    default_dimensions,
    evaluate_candidate,
    tune,
    write_expected_log,
)
from deepspeed_tpu.analysis.audit import (
    STEP_FLAVORS,
    AuditError,
    AuditReport,
    audit_compiled_step,
    audit_engine,
    audit_flavors,
    audit_hlo,
    build_flavor_engine,
    check_recompile,
    compiled_cache_size,
    donated_jit,
)

__all__ = [
    "aliased_param_numbers", "collective_bytes", "collective_ops",
    "computation_multipliers", "estimate_peak_memory",
    "host_transfer_ops",
    "input_output_aliases", "ring_send_bytes", "split_computations",
    "while_loops",
    "CollectiveSite", "ReshardEvent", "check_divergent_collectives",
    "check_unordered_permutes", "collect_collectives", "input_specs_of",
    "propagate_partition_specs", "trace_jaxpr",
    "RULE_IDS", "RULES", "SEV_ERROR", "SEV_INFO", "SEV_WARNING",
    "Finding", "StepContext", "run_rules",
    "STEP_FLAVORS", "AuditError", "AuditReport", "audit_compiled_step",
    "audit_engine",
    "audit_flavors", "audit_hlo", "build_flavor_engine",
    "check_recompile", "compiled_cache_size", "donated_jit",
    "PLATFORMS", "REJECT_PEAK_MEMORY", "Platform", "StepCost",
    "dot_flops", "estimate_step_cost", "resolve_platform",
    "REJECT_BUILD_ERROR", "REJECT_RULE_FINDINGS", "Choice",
    "TuneResult", "default_dimensions", "evaluate_candidate", "tune",
    "write_expected_log",
]
