"""Compiled-program audit subsystem (see docs/analysis.md).

Static analysis over the HLO of compiled train steps: trip-count-aware
collective accounting, donation/aliasing audits, ZeRO byte budgets,
dtype hygiene, host-transfer and recompile detection. The parser lives
in `analysis/hlo.py`, the declarative rule catalog in
`analysis/rules.py`, and the orchestrator + stock-flavor builders in
`analysis/audit.py`; ``bin/ds_tpu_audit`` fronts it all from the
command line.
"""

from deepspeed_tpu.analysis.hlo import (
    aliased_param_numbers,
    collective_bytes,
    collective_ops,
    computation_multipliers,
    host_transfer_ops,
    input_output_aliases,
    ring_send_bytes,
    split_computations,
    while_loops,
)
from deepspeed_tpu.analysis.rules import (
    RULE_IDS,
    RULES,
    SEV_ERROR,
    SEV_INFO,
    SEV_WARNING,
    Finding,
    StepContext,
    run_rules,
)
from deepspeed_tpu.analysis.audit import (
    STEP_FLAVORS,
    AuditError,
    AuditReport,
    audit_compiled_step,
    audit_engine,
    audit_flavors,
    audit_hlo,
    build_flavor_engine,
    check_recompile,
    compiled_cache_size,
    donated_jit,
)

__all__ = [
    "aliased_param_numbers", "collective_bytes", "collective_ops",
    "computation_multipliers", "host_transfer_ops",
    "input_output_aliases", "ring_send_bytes", "split_computations",
    "while_loops",
    "RULE_IDS", "RULES", "SEV_ERROR", "SEV_INFO", "SEV_WARNING",
    "Finding", "StepContext", "run_rules",
    "STEP_FLAVORS", "AuditError", "AuditReport", "audit_compiled_step",
    "audit_engine",
    "audit_flavors", "audit_hlo", "build_flavor_engine",
    "check_recompile", "compiled_cache_size", "donated_jit",
]
