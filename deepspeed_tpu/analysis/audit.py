"""Audit orchestrator: lower a compiled step, run the rule catalog.

Three entry points, layered:

- ``audit_hlo(hlo_text, **ctx)`` — rules over HLO text you already have.
- ``audit_engine(engine, batch)`` — lower a live engine's compiled train
  step (any flavor: dense, ZeRO-1/2/3, offload, quantized, onebit,
  pipeline), build the :class:`StepContext` from the engine's own
  config, and run the catalog plus the recompile detector.
- ``audit_flavors(...)`` — build toy engines for the stock step flavors
  and audit each; backs ``bin/ds_tpu_audit`` and the zero-findings pins
  in ``tests/unit/test_audit_rules.py``.

``donated_jit`` is the declaration side of the donation audit: the
engine's step factories jit through it so the *declared*
``donate_argnums`` ride on the compiled callable
(``_ds_donate_argnums``) where the audit can diff them against the
executable's actual ``input_output_alias`` map.
"""

import dataclasses
import json
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.parallel.collectives import record_collective_sites

from deepspeed_tpu.analysis.hlo import (
    aliased_param_numbers,
    collective_bytes,
    estimate_peak_memory,
    ring_send_bytes,
    while_loops,
)
from deepspeed_tpu.analysis.jaxpr import (
    check_divergent_collectives,
    check_unordered_permutes,
    input_specs_of,
    propagate_partition_specs,
    trace_jaxpr,
)
from deepspeed_tpu.analysis.rules import (
    SEV_ERROR,
    Finding,
    StepContext,
    run_rules,
)

# The engine's seven stock compiled-step flavors, auditable end-to-end.
STEP_FLAVORS = ("dense", "zero1", "zero2", "zero3", "offload", "quantized",
                "pipeline")
# Extra toy flavors the CLI accepts but the default sweep (and the
# un-slow flavor test matrix) skips — heavier compiles exercising
# specific subsystems. `pipeline_tp` runs pipe x model x data with
# tensor_parallel.overlap on, driving the overlap rule end-to-end;
# `fp8` runs GPT-2-tiny with fp8 delayed-scaling matmuls + the
# quantized ZeRO-3 gather wire, driving the fp8 rule end-to-end;
# `decode` runs the serving engine (`inference/`) through a scripted
# continuous-batching stream across two seq buckets and audits the
# compiled decode program: zero in-loop recompiles, cache-dtype
# hygiene, and donation of the ring-buffer KV cache.
# `speculative` drives the self-speculative serving engine
# (`inference/speculative.py`) through the same churn streams on BOTH
# kv layouts and audits the pinned three-program contract (prefill /
# draft / verify, plain decode at zero entries), the draft-truncation
# flop ratio, accept-loop invariants, and host-transfer hygiene of the
# draft and verify programs.
# `disagg` builds one prefill-tier and one decode-tier engine
# (heterogeneous max_batch), streams requests through the synchronous
# disaggregation coordinator, and audits the ONE-program-per-tier
# compile pins, the cross-tier handoff geometry, and host-transfer
# hygiene of the decode tier's steady-state program.
EXTRA_FLAVORS = ("pipeline_tp", "fp8", "decode", "speculative",
                 "disagg")


class AuditError(RuntimeError):
    """Raised by the engine when ``analysis.fail_on_findings`` is set and
    the compile-time audit found error-severity findings."""

    def __init__(self, report):
        super().__init__(report.to_text())
        self.report = report


def donated_jit(fn, donate_argnums=()):
    """``jax.jit`` that records its declared donations on the wrapper.

    The stamp (``_ds_donate_argnums``) makes the engine's donation
    *intent* machine-readable so the donation audit can diff it against
    the compiled executable's actual input/output aliasing — a plain
    ``jax.jit`` call site that silently drops ``donate_argnums`` loses
    the stamp too, which the audit reports as un-donated state."""
    jitted = jax.jit(fn, donate_argnums=tuple(donate_argnums))
    try:
        jitted._ds_donate_argnums = tuple(donate_argnums)
    except Exception:  # pragma: no cover - jit wrappers accept attrs today
        pass
    return jitted


@dataclass
class AuditReport:
    flavor: str
    findings: list
    stats: dict = field(default_factory=dict)
    # compiled HLO text of the audited step — kept off to_dict()/to_json()
    # (it can be megabytes); the autotuner's cost model reads it.
    hlo_text: str = field(default="", repr=False)

    @property
    def ok(self):
        """No error-severity findings (warnings don't fail a run)."""
        return all(f.severity != SEV_ERROR for f in self.findings)

    def to_dict(self):
        return {"flavor": self.flavor, "ok": self.ok,
                "findings": [f.to_dict() for f in self.findings],
                "stats": self.stats}

    def to_json(self, indent=2):
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    def to_text(self):
        lines = [f"[{self.flavor}] "
                 + ("OK — no findings" if not self.findings else
                    f"{len(self.findings)} finding(s)")]
        cb = self.stats.get("collective_bytes") or {}
        if cb:
            vols = ", ".join(f"{op} {b / 1e6:.2f}MB"
                             for op, b in sorted(cb.items())
                             if op != "total")
            lines.append(f"  collectives/step (trip-aware): "
                         f"{vols or 'none'}; total {cb.get('total', 0) / 1e6:.2f}MB")
        if "donated_expected" in self.stats:
            lines.append(
                f"  donation: {self.stats.get('donated_aliased', 0)}"
                f"/{self.stats['donated_expected']} donated buffers aliased")
        if "while_loops" in self.stats:
            n = self.stats["while_loops"]
            unknown = self.stats.get("unknown_trip_counts", 0)
            lines.append(f"  loops: {n} while loop(s), "
                         + ("all trip counts known" if not unknown
                            else f"{unknown} with UNKNOWN trip count"))
        if "compile_cache_size" in self.stats:
            lines.append(f"  recompiles: cache size "
                         f"{self.stats['compile_cache_size']} after "
                         f"{self.stats.get('steps_run', 0)} step(s)")
        kernel_blocks = []
        if self.stats.get("kernels"):
            kernel_blocks.append((None, self.stats["kernels"]))
        for layout, lstats in (self.stats.get("layouts") or {}).items():
            if lstats.get("kernels"):
                kernel_blocks.append((layout, lstats["kernels"]))
        for layout, ks in kernel_blocks:
            tag = f" [{layout}]" if layout else ""
            for kname, kd in (ks.get("kernels") or {}).items():
                lines.append(
                    f"  kernel{tag} {kname}: grid {tuple(kd['grid'])}, "
                    f"VMEM {kd['vmem_bytes'] / 1024:.1f}KB / "
                    f"{ks.get('vmem_budget_bytes', 0) / (1 << 20):.0f}MB, "
                    f"elided DMA {kd['elided_dma_fraction']:.1%}")
            if ks.get("expected_elision") is not None:
                lines.append(f"  elision contract{tag}: >= "
                             f"{ks['expected_elision']:.1%} proven")
        for f in self.findings:
            lines.append(f"  - [{f.severity}] {f.rule}: {f.message}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# lowering and context extraction
# ---------------------------------------------------------------------------

def _lower_step(fn, args):
    """Lower+compile a jitted step; map declared donations through arg
    flattening and unused-arg pruning onto HLO entry-parameter numbers.

    Returns ``(hlo_text, expected_donated_params, donated_param_info)``.
    ``args_info`` carries per-flat-leaf donation flags; the executable's
    ``_kept_var_idx`` says which flat leaves survived pruning (HLO
    parameter i is the i-th kept leaf). Pruned leaves never reach the
    executable so they are no HBM concern and drop out of the
    expectation.
    """
    lowered = fn.lower(*args)
    compiled = lowered.compile()
    hlo_text = compiled.as_text()
    info_leaves = jax.tree_util.tree_leaves(
        lowered.args_info, is_leaf=lambda x: hasattr(x, "donated"))
    kept = getattr(getattr(compiled, "_executable", None),
                   "_kept_var_idx", None)
    kept = sorted(kept) if kept is not None else range(len(info_leaves))
    expected, pinfo = set(), {}
    for hlo_param, flat_idx in enumerate(kept):
        if flat_idx >= len(info_leaves):
            continue
        leaf = info_leaves[flat_idx]
        if not getattr(leaf, "donated", False):
            continue
        expected.add(hlo_param)
        shape = tuple(getattr(leaf, "shape", ()) or ())
        dtype = getattr(leaf, "dtype", None)
        itemsize = np.dtype(dtype).itemsize if dtype is not None else 1
        nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize \
            if shape else itemsize
        pinfo[hlo_param] = {"shape": list(shape), "dtype": str(dtype),
                            "bytes": nbytes}
    return hlo_text, expected, pinfo


def _engine_flavor(engine):
    cfg = engine._config
    if getattr(engine.loss_fn, "direct_value_and_grad", None) is not None:
        return "pipeline"
    if engine._offload:
        return "offload"
    if cfg.comm_quantization.enabled:
        return "quantized"
    if engine.optimizer_name == "onebitadam" or \
            (engine.optimizer_name or "").lower() == "onebitadam":
        return "onebit"
    if engine.sparse_gradients_enabled():
        return "sparse"
    fp8 = getattr(cfg, "fp8", None)
    if fp8 is not None and (fp8.enabled or fp8.wire_enabled):
        return "fp8"
    stage = engine.zero_optimization_stage()
    return f"zero{stage}" if stage else "dense"


def _engine_fn_args(engine, placed, rng, lr):
    """The compiled step callable and the exact lowering argument list —
    mirrors ``train_batch``'s call so ``lower()`` hits the jit cache."""
    step = engine._compiled_train_step
    fn = getattr(step, "inner", step)
    if engine._offload:
        args = [engine.params, engine.device_state, placed, rng, lr]
    else:
        args = [engine.params, engine.opt_state, engine.device_state,
                placed, rng, lr]
        if getattr(step, "fp8", False):
            # fp8 amax-state threading; discovery is idempotent, so an
            # audit that lowers before the first step call allocates it.
            args.append(engine._ensure_fp8_state(placed, rng))
        elif hasattr(step, "inner"):   # error-feedback residual threading
            args.append(engine._qcomm_residuals)
    if engine._fault_arg:
        args.append(jnp.asarray(1.0))
    return fn, tuple(args)


def _jaxpr_facts(fn, args):
    """Trace-time facts for the rule catalog: the three jaxpr passes
    over the step's closed jaxpr (a retrace, never a compile). Returns
    ``{divergent, unordered, reshard_events}`` — or all-None on a trace
    failure, which downgrades the trace-time rules to not-run rather
    than failing the whole audit."""
    try:
        with record_collective_sites() as sites:
            closed = trace_jaxpr(fn, args)
        divergent = check_divergent_collectives(closed)
        unordered = check_unordered_permutes(closed)
        _, events = propagate_partition_specs(closed,
                                              input_specs_of(args))
    except Exception as exc:  # pragma: no cover - defensive
        return {"divergent": None, "unordered": None,
                "reshard_events": None, "collective_sites": None,
                "trace_error": str(exc)}
    return {
        "divergent": divergent,
        "unordered": unordered,
        "reshard_events": [
            {"kind": e.kind, "primitive": e.primitive,
             "path": list(e.path), "dim": e.dim, "bytes": e.bytes,
             "specs": [list(s) for s in e.specs]}
            for e in events],
        "collective_sites": [dataclasses.asdict(s) for s in sites],
    }


def _replicated_state_leaves(engine):
    """Large optimizer-state leaves placed fully replicated — under
    ZeRO >= 1 these mean the partition spec never attached (the
    resharding rule sizes and reports them)."""
    if engine._offload or engine.opt_state is None:
        return []
    leaves = []
    flat, _ = jax.tree_util.tree_flatten_with_path(engine.opt_state)
    for path, leaf in flat:
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is None or any(e is not None for e in tuple(spec)):
            continue
        nbytes = int(getattr(leaf, "nbytes", 0) or 0)
        leaves.append({"path": jax.tree_util.keystr(path),
                       "bytes": nbytes,
                       "shape": list(getattr(leaf, "shape", ()))})
    return leaves


def _engine_context(engine, hlo_text, expected, pinfo, jaxpr_facts=None):
    cfg = engine._config
    dtype = engine.compute_dtype
    compute = ("bf16" if dtype == jnp.bfloat16 else
               "f16" if dtype == jnp.float16 else "f32")
    param_bytes = sum(
        int(np.prod(l.shape, dtype=np.int64)) * 4
        for l in jax.tree_util.tree_leaves(engine.params))
    flavor = _engine_flavor(engine)
    skip = set()
    if flavor in ("onebit", "sparse"):
        # Both replace the gradient exchange with their own compressed /
        # CSR wire formats — the generic ZeRO/dtype budgets don't model
        # them (their exact ratios are pinned by dedicated tests).
        skip |= {"zero_budget", "dtype_hygiene"}
    step = engine._compiled_train_step
    declared = getattr(getattr(step, "inner", step),
                       "_ds_donate_argnums", None)
    tp = getattr(cfg, "tensor_parallel", None)
    facts = jaxpr_facts or {}
    analysis_cfg = getattr(cfg, "analysis", None)
    budget_mb = float(getattr(analysis_cfg, "peak_memory_budget_mb", 0)
                      or 0)
    plan = getattr(engine, "_zero3_plan", None)
    return StepContext(
        hlo_text=hlo_text,
        flavor=flavor,
        n_devices=int(engine.mesh.shape.get("data", 1)),
        compute_dtype=compute,
        zero_stage=engine.zero_optimization_stage(),
        comm_quantized=cfg.comm_quantization.enabled,
        offload=engine._offload,
        pipeline=(flavor == "pipeline"),
        param_bytes=param_bytes,
        expected_donated_params=expected,
        donated_param_info=pinfo,
        declared_donate_argnums=declared,
        overlap_enabled=bool(tp is not None and tp.overlap_enabled),
        overlap_chunks=int(tp.overlap_chunks) if tp is not None else 1,
        fp8_enabled=bool(cfg.fp8.enabled),
        fp8_wire_dtype=cfg.fp8.active_wire_dtype(),
        jaxpr_divergent=facts.get("divergent"),
        jaxpr_unordered=facts.get("unordered"),
        reshard_events=facts.get("reshard_events"),
        collective_sites=facts.get("collective_sites"),
        zero3_gather_leaves=int(plan.gather_leaves) if plan else 0,
        zero3_gather_chunks=int(plan.gather_chunks) if plan else 1,
        zero3_max_gather_bytes=int(plan.max_gather_bytes) if plan else 0,
        replicated_leaves=_replicated_state_leaves(engine),
        peak_memory=estimate_peak_memory(hlo_text),
        peak_budget_bytes=int(budget_mb * (1 << 20)),
        skip_rules=skip)


def compiled_cache_size(engine):
    """Entries in the compiled train step's jit cache (None if the jit
    wrapper doesn't expose it). 1 after any number of same-shape steps —
    growth means something recompiles every call."""
    step = engine._compiled_train_step
    if step is None:
        return None
    fn = getattr(step, "inner", step)
    cache_size = getattr(fn, "_cache_size", None)
    try:
        return int(cache_size()) if callable(cache_size) else None
    except Exception:
        return None


def check_recompile(engine, baseline=1):
    """Recompile detector: Finding when the step's jit cache outgrew the
    expected single entry (shape-unstable batches, dtype drift, a python
    value captured as a tracer-changing constant, ...)."""
    n = compiled_cache_size(engine)
    if n is None or n <= baseline:
        return []
    return [Finding(
        "recompile", SEV_ERROR,
        f"compiled train step has {n} cache entries (expected "
        f"{baseline}) — the step recompiled during the run",
        {"cache_size": n, "expected": baseline})]


# ---------------------------------------------------------------------------
# audit entry points
# ---------------------------------------------------------------------------

def audit_hlo(hlo_text, rules=None, **ctx_kwargs):
    """Run the rule catalog over raw HLO text (no engine needed).

    The trace-time rules (`deadlock`, the spec-flow half of
    `resharding`) need a jaxpr and stay not-run here; `peak_memory`
    works from the text alone."""
    ctx = StepContext(hlo_text=hlo_text, **ctx_kwargs)
    if ctx.peak_memory is None:
        ctx.peak_memory = estimate_peak_memory(hlo_text)
    report = AuditReport(flavor=ctx.flavor, findings=run_rules(ctx, rules))
    report.stats = _hlo_stats(hlo_text, ctx)
    return report


def _hlo_stats(hlo_text, ctx):
    loops = while_loops(hlo_text)
    stats = {
        "collective_bytes": collective_bytes(hlo_text),
        "collective_bytes_by_dtype": collective_bytes(hlo_text,
                                                      by_dtype=True),
        "collective_bytes_flat": collective_bytes(hlo_text,
                                                  trip_aware=False),
        "ring_send_bytes": ring_send_bytes(hlo_text,
                                           max(ctx.n_devices, 2)),
        "while_loops": len(loops),
        "unknown_trip_counts": sum(1 for l in loops
                                   if l["trip_count"] is None),
        "trip_counts": [l["trip_count"] for l in loops],
        "param_bytes": ctx.param_bytes,
    }
    if ctx.expected_donated_params is not None:
        aliased = aliased_param_numbers(hlo_text)
        stats["donated_expected"] = len(ctx.expected_donated_params)
        stats["donated_aliased"] = len(
            ctx.expected_donated_params & aliased)
    if ctx.peak_memory:
        stats["peak_memory"] = {
            k: ctx.peak_memory.get(k, 0)
            for k in ("peak_bytes", "temp_peak_bytes",
                      "parameter_bytes", "output_bytes",
                      "donated_output_bytes")}
    if ctx.jaxpr_divergent is not None:
        stats["jaxpr"] = {
            "divergent_collectives": len(ctx.jaxpr_divergent),
            "unordered_permutes": len(ctx.jaxpr_unordered or ()),
            "reshard_conflicts": len(ctx.reshard_events or ()),
        }
        if ctx.collective_sites is not None:
            stats["jaxpr"]["collective_sites"] = [
                dict(s) for s in ctx.collective_sites]
    return stats


def audit_compiled_step(engine, placed, rng, lr, rules=None):
    """In-engine compile-time audit: lower the just-compiled step with
    the live call's exact avals (so the engine's own step call right
    after is a jit-cache hit) and run the rule catalog. Backs the
    opt-in ``analysis`` config block (`runtime/engine.py`)."""
    fn, args = _engine_fn_args(engine, placed, rng, lr)
    hlo_text, expected, pinfo = _lower_step(fn, args)
    ctx = _engine_context(engine, hlo_text, expected, pinfo,
                          jaxpr_facts=_jaxpr_facts(fn, args))
    report = AuditReport(flavor=ctx.flavor, findings=run_rules(ctx, rules))
    report.stats = _hlo_stats(hlo_text, ctx)
    report.hlo_text = hlo_text
    return report


def audit_engine(engine, batch, rules=None, steps=0):
    """Audit a live engine's compiled train step.

    Runs one ``train_batch`` if the step isn't compiled yet (lazy
    compile), plus ``steps`` more for the recompile detector, then
    lowers the step with the exact argument avals ``train_batch`` uses
    (a jit-cache hit, not a second compile) and runs the rule catalog.
    """
    t0 = time.perf_counter()
    steps_run = 0
    if engine._compiled_train_step is None:
        engine.train_batch(batch)
        steps_run += 1
    for _ in range(steps):
        engine.train_batch(batch)
        steps_run += 1
    placed = engine._shard_batch(batch)
    rng = jax.random.PRNGKey(0)
    lr = jnp.asarray(1e-3, jnp.float32)
    fn, args = _engine_fn_args(engine, placed, rng, lr)
    hlo_text, expected, pinfo = _lower_step(fn, args)
    ctx = _engine_context(engine, hlo_text, expected, pinfo,
                          jaxpr_facts=_jaxpr_facts(fn, args))
    findings = run_rules(ctx, rules)
    if (rules is None or "recompile" in rules) \
            and "recompile" not in ctx.skip_rules:
        findings.extend(check_recompile(engine))
    report = AuditReport(flavor=ctx.flavor, findings=findings)
    report.stats = _hlo_stats(hlo_text, ctx)
    report.hlo_text = hlo_text
    report.stats["compile_cache_size"] = compiled_cache_size(engine)
    report.stats["steps_run"] = steps_run
    report.stats["audit_wall_s"] = round(time.perf_counter() - t0, 3)
    return report


# ---------------------------------------------------------------------------
# stock flavor builders (toy engines; used by the CLI, tests, and bench)
# ---------------------------------------------------------------------------

_TOY_HIDDEN = 256
_TOY_LAYERS = 4


def _toy_params_and_loss(hidden=_TOY_HIDDEN, nlayers=_TOY_LAYERS):
    keys = jax.random.split(jax.random.PRNGKey(0), nlayers)
    params = {
        f"linear_{i}": {
            "kernel": jax.random.normal(
                k, (hidden, hidden), jnp.float32) * 0.02,
            "bias": jnp.zeros((hidden,), jnp.float32),
        }
        for i, k in enumerate(keys)
    }

    def loss_fn(params, batch, rng=None):
        x = batch["x"]
        for i in range(nlayers):
            layer = params[f"linear_{i}"]
            x = x @ layer["kernel"] + layer["bias"]
            if i < nlayers - 1:
                x = jax.nn.relu(x)
        return jnp.mean(jnp.square(x - batch["y"]))

    return params, loss_fn


def _toy_batch(rows=16, hidden=_TOY_HIDDEN):
    rng = np.random.default_rng(0)
    return {"x": rng.normal(size=(rows, hidden)).astype(np.float32),
            "y": rng.normal(size=(rows, hidden)).astype(np.float32)}


def _dense_family_config(flavor):
    cfg = {"train_batch_size": 16,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "steps_per_print": 10 ** 9}
    if flavor == "dense":
        cfg["bf16"] = {"enabled": True}
    elif flavor in ("zero1", "zero2"):
        cfg["bf16"] = {"enabled": True}
        cfg["zero_optimization"] = {"stage": int(flavor[-1])}
    elif flavor == "zero3":
        # Explicit gather-on-use path with ring chunking so the audit
        # exercises the stage-3 overlap/budget rules end-to-end.
        cfg["bf16"] = {"enabled": True}
        cfg["zero_optimization"] = {"stage": 3, "gather_chunks": 2}
    elif flavor == "offload":
        cfg["bf16"] = {"enabled": True}
        cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
    elif flavor == "quantized":
        # fp32 compute keeps the dense baseline's wire dtype — the
        # quantized audit checks the int8 replacement, not bf16 hygiene.
        cfg["comm_quantization"] = {"enabled": True, "chunk_size": 512,
                                    "bucket_mb": 4}
    else:
        raise ValueError(f"unknown dense-family flavor {flavor!r}")
    return cfg


def build_flavor_engine(flavor, config_overrides=None):
    """``(engine, batch)`` for one stock step flavor, toy-sized so all
    seven compile inside a CPU test budget."""
    import deepspeed_tpu

    if flavor == "pipeline":
        from deepspeed_tpu.models.gpt2 import gpt2_tiny
        from deepspeed_tpu.models.gpt2_pipe import gpt2_pipeline_module
        from deepspeed_tpu.parallel.mesh import build_mesh
        rows, seq = 8, 16
        mesh = build_mesh({"pipe": 2, "data": 4},
                          devices=jax.devices()[:8])
        module = gpt2_pipeline_module(gpt2_tiny(), seq_len=seq)
        cfg = {"train_batch_size": rows,
               "gradient_accumulation_steps": 2,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "steps_per_print": 10 ** 9}
        cfg.update(config_overrides or {})
        engine, _, _, _ = deepspeed_tpu.initialize(
            config=cfg, model=module, mesh=mesh)
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(
            0, 255, (rows, seq)).astype(np.int32)}
        return engine, batch

    if flavor == "pipeline_tp":
        # pipe x model x data with tensor_parallel.overlap on: the 1F1B
        # step whose row-parallel combines lower to chunked ppermute
        # rings — the flavor the overlap rule audits end-to-end.
        from deepspeed_tpu.parallel.mesh import build_mesh
        from deepspeed_tpu.parallel.pipe_tp import tp_pipeline_module
        rows, seq = 8, 16
        mesh = build_mesh({"pipe": 2, "model": 2, "data": 2},
                          devices=jax.devices()[:8])
        module = tp_pipeline_module(vocab=64, d_model=16, n_head=4,
                                    seq_len=seq, n_blocks=2, num_stages=2)
        cfg = {"train_batch_size": rows,
               "gradient_accumulation_steps": 2,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "steps_per_print": 10 ** 9,
               "tensor_parallel": {"overlap": {"enabled": True,
                                               "chunks": 4}}}
        cfg.update(config_overrides or {})
        engine, _, _, _ = deepspeed_tpu.initialize(
            config=cfg, model=module, mesh=mesh)
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(
            0, 64, (rows, seq)).astype(np.int32)}
        return engine, batch

    if flavor == "fp8":
        # fp8 delayed-scaling matmuls on GPT-2-tiny (the model whose
        # Dense layers route through `ops/fp8.py:fp8_dot_general`) plus
        # the quantized ZeRO-3 gather wire — the flavor the fp8 rule
        # audits end-to-end.
        from deepspeed_tpu.models.gpt2 import (
            GPT2LMHead, gpt2_tiny, init_gpt2_params, make_gpt2_loss_fn)
        rows, seq = 8, 16
        model = GPT2LMHead(gpt2_tiny())
        params = init_gpt2_params(model, jax.random.PRNGKey(0))
        cfg = {"train_batch_size": rows,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "steps_per_print": 10 ** 9,
               "bf16": {"enabled": True},
               "zero_optimization": {"stage": 3, "gather_chunks": 2},
               "fp8": {"enabled": True,
                       "wire": {"enabled": True, "dtype": "f8e4m3fn"}}}
        cfg.update(config_overrides or {})
        engine, _, _, _ = deepspeed_tpu.initialize(
            config=cfg, loss_fn=make_gpt2_loss_fn(model), params=params)
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(
            0, 255, (rows, seq)).astype(np.int32)}
        return engine, batch

    cfg = _dense_family_config(flavor)
    cfg.update(config_overrides or {})
    params, loss_fn = _toy_params_and_loss()
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, loss_fn=loss_fn, params=params)
    return engine, _toy_batch()


# The sub-pallas_call rule subset (`analysis/kernels.py` facts); the
# kernel-only flavors run exactly these.
KERNEL_RULES = ("kernel_vmem", "kernel_tiling", "kernel_dma")


def _kernel_analysis_for(fn, args, engine):
    """Kernel analysis of a serving program at representative occupancy.

    ``decode_lowering_args()`` carries all-zero positions (and, paged,
    all-trash page tables) — correct avals for lowering, but degenerate
    for a DMA-elision proof (everything clamps to block 0). Replace
    them with a half-full scenario: row ``b`` at position
    ``(b+1) * max_seq / (2 * max_batch)`` and, for the paged layout,
    distinct live page-table entries (no cross-row physical sharing, so
    elision is attributable to the clamp alone). Returns
    ``(KernelAnalysis, expected_elision)`` where the expectation is the
    scenario's dead-block fraction (`kernels.ring_dead_block_fraction`)
    — the contract `rules.rule_kernel_dma` enforces.
    """
    from deepspeed_tpu.analysis.kernels import (
        analyze_kernels, ring_dead_block_fraction)

    args = list(args)
    B = engine.spec.max_batch
    max_seq = engine.max_seq
    pos = np.array([(b + 1) * max_seq // (2 * B) for b in range(B)],
                   np.int32)
    args[3] = jnp.asarray(pos)                 # positions operand
    if engine.kv_layout == "paged":
        ppr = engine.pages_per_row
        pt = (np.arange(B * ppr).reshape(B, ppr)
              % (engine.n_pages - 1)) + 1     # live, distinct, non-trash
        args[4] = jnp.asarray(pt.astype(np.int32))
    ana = analyze_kernels(fn, tuple(args))
    expected = ring_dead_block_fraction(
        pos, max_seq, engine.attention_block_k) if ana.kernels else None
    return ana, expected


def audit_decode(rules=None, config_overrides=None, kv_cache_dtype=None,
                 attention_impl="flash", kv_layout="ring",
                 kernels=False):
    """Audit the serving engine's compiled decode program.

    Builds a tiny :class:`~deepspeed_tpu.inference.engine.
    InferenceEngine`, drives a scripted continuous-batching stream that
    crosses two seq buckets with admission/eviction (more requests than
    cache rows, mixed prompt lengths and generation budgets), then
    lowers the decode program through its live avals (a jit-cache hit)
    and runs the rule catalog over it — the `decode` rule pins zero
    in-loop recompiles and cache-dtype hygiene, the generic donation
    rule pins that the ring-buffer KV cache actually aliases in place,
    and the `flash_decode` rule pins that the stock flash attention
    path (``attention_impl="flash"``, the default) actually deleted the
    dense full-cache contraction from the lowered program.

    With ``kv_layout="paged"`` the scripted stream additionally churns
    the page allocator end to end: shared-prefix admissions (radix
    hits), a pool-pressure request that rides the eviction ladder, a
    parked session that the pressure evacuates to host RAM, and a
    follow-up that pages it back in and resumes mid-prompt — then the
    `decode` rule pins that the post-churn program still lowered zero
    host transfers and the jit caches never grew past the 2-compile
    contract.

    ``kernels=True`` additionally runs the sub-``pallas_call`` analyzer
    (`analysis/kernels.py`) over the decode program at a representative
    half-full occupancy and arms the ``kernel_vmem`` /
    ``kernel_tiling`` / ``kernel_dma`` rules — including the
    DMA-elision proof that the clamped index maps turn the scenario's
    dead cache blocks into elided fetches.
    """
    import jax.numpy as jnp
    from deepspeed_tpu.inference.cache import cache_dtype_census
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.scheduler import (
        ContinuousBatchingScheduler, Request)
    from deepspeed_tpu.models.gpt2 import GPT2LMHead, gpt2_tiny

    t0 = time.perf_counter()
    cfg = gpt2_tiny(n_embd=32, dtype=jnp.float32)
    model = GPT2LMHead(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    inf_cfg = {"max_batch": 2, "seq_buckets": (16, 32),
               "prefill_chunk": 4, "kv_cache_dtype": kv_cache_dtype,
               "attention_impl": attention_impl, "attention_block_k": 8,
               "kv_layout": kv_layout}
    inf_cfg.update(config_overrides or {})
    engine = InferenceEngine(model, params, config=inf_cfg)
    sched = ContinuousBatchingScheduler(engine)
    rng = np.random.default_rng(0)
    paged = engine.kv_layout == "paged"
    if paged:
        # Allocator-churn stream: r0/r1 share a >page_size prefix (r1
        # is a radix hit on r0's interned pages), r2 parks its pages
        # under a session id, r3's 30-token prompt squeezes the pool
        # (pressure ladder: radix eviction, then host evacuation of
        # r2's parked pages), r4 re-hits the shared prefix open-loop.
        base = rng.integers(0, cfg.vocab_size, 12).tolist()
        stream = [
            Request("r0", base + rng.integers(
                0, cfg.vocab_size, 3).tolist(), max_new_tokens=4),
            Request("r1", base + rng.integers(
                0, cfg.vocab_size, 5).tolist(), max_new_tokens=5),
            Request("r2", rng.integers(0, cfg.vocab_size, 6).tolist(),
                    max_new_tokens=4, session_id="s0"),
            Request("r3", rng.integers(0, cfg.vocab_size, 30).tolist(),
                    max_new_tokens=10),
            Request("r4", base + rng.integers(
                0, cfg.vocab_size, 2).tolist(), max_new_tokens=3,
                    arrival_step=3)]
        completions = sched.run(stream)
        # Session resume: extend s0's history (prompt + every token
        # that fed a decode step) so admission pages the parked KV
        # back in and restarts prefill mid-prompt.
        s0 = {c.rid: c for c in completions}["r2"]
        follow = stream[2].prompt + s0.tokens + rng.integers(
            0, cfg.vocab_size, 2).tolist()
        completions = sched.run([Request("r5", follow, max_new_tokens=3,
                                         session_id="s0")])
    else:
        # 5 requests over 2 rows: slot recycling, both buckets, a
        # clamped over-budget request that length-evicts, and an
        # open-loop arrival.
        stream = [Request("r0",
                          rng.integers(0, cfg.vocab_size, 3).tolist(),
                          max_new_tokens=4),
                  Request("r1",
                          rng.integers(0, cfg.vocab_size, 20).tolist(),
                          max_new_tokens=6),
                  Request("r2",
                          rng.integers(0, cfg.vocab_size, 2).tolist(),
                          max_new_tokens=3, arrival_step=3),
                  Request("r3",
                          rng.integers(0, cfg.vocab_size, 30).tolist(),
                          max_new_tokens=10),
                  Request("r4",
                          rng.integers(0, cfg.vocab_size, 6).tolist(),
                          max_new_tokens=5)]
        completions = sched.run(stream)
    hlo_text, expected, pinfo = _lower_step(engine._decode,
                                            engine.decode_lowering_args())
    kernel_ana = kernel_expected = None
    if kernels:
        kernel_ana, kernel_expected = _kernel_analysis_for(
            engine._decode, engine.decode_lowering_args(), engine)
    census = cache_dtype_census(engine.cache)
    if paged:
        payload_shape = (engine.spec.n_pages, engine.spec.page_size,
                         engine.spec.n_head, engine.spec.head_dim)
        page_facts = {"page_size": engine.page_size,
                      "n_pages": engine.n_pages,
                      "pages_per_row": engine.pages_per_row,
                      "max_seq": engine.max_seq}
    else:
        payload_shape = (engine.spec.max_batch, engine.spec.max_seq,
                         engine.spec.n_head, engine.spec.head_dim)
        page_facts = None
    ctx = StepContext(
        hlo_text=hlo_text, flavor="decode",
        compute_dtype="f32" if cfg.dtype == jnp.float32 else "bf16",
        expected_donated_params=expected, donated_param_info=pinfo,
        declared_donate_argnums=getattr(
            engine._decode, "_ds_donate_argnums", None),
        decode_compile_counts=engine.compile_counts(),
        decode_kv_cache_dtype=engine.kv_cache_dtype,
        decode_cache_census=census,
        decode_attention_impl=engine.attention_impl,
        decode_cache_payload_shape=payload_shape,
        decode_platform=jax.devices()[0].platform,
        decode_kv_layout=engine.kv_layout,
        decode_page_facts=page_facts,
        kernel_analysis=kernel_ana,
        kernel_expected_elision=kernel_expected,
        skip_rules={"recompile"})
    findings = run_rules(ctx, rules)
    findings.extend(engine.recompile_findings())
    report = AuditReport(flavor="decode", findings=findings)
    report.stats = _hlo_stats(hlo_text, ctx)
    report.hlo_text = hlo_text
    report.stats["compile_counts"] = engine.compile_counts()
    report.stats["completions"] = len(completions)
    report.stats["finish_reasons"] = sorted(
        c.finish_reason for c in completions)
    report.stats["cache"] = engine.cache_facts()
    report.stats["attention"] = {"impl": engine.attention_impl,
                                 "block_k": engine.attention_block_k}
    if paged:
        report.stats["paging"] = sched.paging.facts()
    if kernel_ana is not None:
        report.stats["kernels"] = kernel_ana.to_dict()
        report.stats["kernels"]["expected_elision"] = kernel_expected
    report.stats["audit_wall_s"] = round(time.perf_counter() - t0, 3)
    return report


def _xla_flops(fn, args):
    """Compiled-program flop count from XLA cost analysis (0.0 when the
    backend doesn't report one)."""
    try:
        ca = fn.lower(*args).compile().cost_analysis()
    except Exception:
        return 0.0
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return float((ca or {}).get("flops", 0.0) or 0.0)


def audit_speculative(rules=None, config_overrides=None,
                      kv_cache_dtype=None, attention_impl="flash",
                      kv_layout=None, k=3, draft_layers=1, n_layer=4,
                      kernels=False):
    """Audit the self-speculative serving engine end to end.

    Runs :func:`audit_decode`'s scripted churn streams (slot recycling
    and bucket crossing on the ring layout; radix hits, pool pressure,
    host park + mid-prompt resume on the paged layout) with speculation
    enabled, then audits:

    - the pinned THREE-program contract — prefill, draft, verify each
      exactly one jit-cache entry and the plain decode program at ZERO
      (an entry means the scheduler silently fell back mid-stream);
    - draft truncation — XLA cost-analysis flops of the draft step vs
      the full-depth decode step at the same avals must sit near
      ``draft_layers / n_layer``, not near 1.0;
    - accept-loop invariants (``mean_accepted >= 1.0`` by construction,
      ``draft_efficiency`` within [0, 1]);
    - draft/verify program hygiene — donation of the cache operand,
      zero host transfers on the paged layout, and the flash payload
      pins on the T=1 draft step.

    ``kv_layout=None`` (the default, and what the CLI flavor runs)
    sweeps BOTH layouts and merges the findings into one report —
    speculation must survive serve churn on each.
    """
    import jax.numpy as jnp
    from deepspeed_tpu.inference.cache import cache_dtype_census
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.scheduler import (
        ContinuousBatchingScheduler, Request)
    from deepspeed_tpu.models.gpt2 import GPT2LMHead, gpt2_tiny

    t0 = time.perf_counter()
    layouts = (kv_layout,) if kv_layout else ("ring", "paged")
    findings, stats = [], {"layouts": {}}
    hlo_text = ""
    for layout in layouts:
        cfg = gpt2_tiny(n_embd=32, n_layer=n_layer, dtype=jnp.float32)
        model = GPT2LMHead(cfg)
        toks = jnp.zeros((1, 8), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        inf_cfg = {"max_batch": 2, "seq_buckets": (16, 32),
                   "prefill_chunk": 4, "kv_cache_dtype": kv_cache_dtype,
                   "attention_impl": attention_impl,
                   "attention_block_k": 8, "kv_layout": layout,
                   "speculative": {"enabled": True, "k": k,
                                   "draft_layers": draft_layers}}
        inf_cfg.update(config_overrides or {})
        engine = InferenceEngine(model, params, config=inf_cfg)
        spec = engine.speculative
        sched = ContinuousBatchingScheduler(engine)
        rng = np.random.default_rng(0)
        if layout == "paged":
            base = rng.integers(0, cfg.vocab_size, 12).tolist()
            stream = [
                Request("r0", base + rng.integers(
                    0, cfg.vocab_size, 3).tolist(), max_new_tokens=4),
                Request("r1", base + rng.integers(
                    0, cfg.vocab_size, 5).tolist(), max_new_tokens=5),
                Request("r2", rng.integers(
                    0, cfg.vocab_size, 6).tolist(),
                    max_new_tokens=4, session_id="s0"),
                Request("r3", rng.integers(
                    0, cfg.vocab_size, 30).tolist(), max_new_tokens=10),
                Request("r4", base + rng.integers(
                    0, cfg.vocab_size, 2).tolist(), max_new_tokens=3,
                    arrival_step=3)]
            completions = sched.run(stream)
            s0 = {c.rid: c for c in completions}["r2"]
            follow = stream[2].prompt + s0.tokens + rng.integers(
                0, cfg.vocab_size, 2).tolist()
            completions = sched.run(
                [Request("r5", follow, max_new_tokens=3,
                         session_id="s0")])
        else:
            stream = [
                Request("r0", rng.integers(
                    0, cfg.vocab_size, 3).tolist(), max_new_tokens=4),
                Request("r1", rng.integers(
                    0, cfg.vocab_size, 20).tolist(), max_new_tokens=6),
                Request("r2", rng.integers(
                    0, cfg.vocab_size, 2).tolist(),
                    max_new_tokens=3, arrival_step=3),
                Request("r3", rng.integers(
                    0, cfg.vocab_size, 30).tolist(), max_new_tokens=10),
                Request("r4", rng.integers(
                    0, cfg.vocab_size, 6).tolist(), max_new_tokens=5)]
            completions = sched.run(stream)
        compile_counts = engine.compile_counts()
        draft_args = spec.draft_lowering_args()
        draft_hlo, expected, pinfo = _lower_step(spec._draft, draft_args)
        kernel_ana = kernel_expected = None
        if kernels:
            kernel_ana, kernel_expected = _kernel_analysis_for(
                spec._draft, draft_args, engine)
        verify_hlo, v_expected, v_pinfo = _lower_step(
            spec._verify, spec.verify_lowering_args())
        draft_flops = _xla_flops(spec._draft, draft_args)
        full_flops = _xla_flops(engine._decode,
                                engine.decode_lowering_args())
        if layout == "paged":
            payload_shape = (engine.spec.n_pages, engine.spec.page_size,
                             engine.spec.n_head, engine.spec.head_dim)
            page_facts = {"page_size": engine.page_size,
                          "n_pages": engine.n_pages,
                          "pages_per_row": engine.pages_per_row,
                          "max_seq": engine.max_seq}
        else:
            payload_shape = (engine.spec.max_batch, engine.spec.max_seq,
                             engine.spec.n_head, engine.spec.head_dim)
            page_facts = None
        ctx = StepContext(
            hlo_text=draft_hlo, flavor="speculative",
            compute_dtype="f32",
            expected_donated_params=expected, donated_param_info=pinfo,
            declared_donate_argnums=getattr(
                spec._draft, "_ds_donate_argnums", None),
            decode_compile_counts=compile_counts,
            decode_kv_cache_dtype=engine.kv_cache_dtype,
            decode_cache_census=cache_dtype_census(engine.cache),
            decode_attention_impl=engine.attention_impl,
            decode_cache_payload_shape=payload_shape,
            decode_platform=jax.devices()[0].platform,
            decode_kv_layout=engine.kv_layout,
            decode_page_facts=page_facts,
            spec_facts=spec.facts(),
            spec_compile_counts=compile_counts,
            spec_draft_hlo=draft_hlo, spec_verify_hlo=verify_hlo,
            spec_draft_flops=draft_flops, spec_full_flops=full_flops,
            kernel_analysis=kernel_ana,
            kernel_expected_elision=kernel_expected,
            skip_rules={"recompile"})
        layout_findings = run_rules(ctx, rules)
        # verify program: full-depth dense by design (the flash kernel
        # is a T=1 specialization), so only the donation pin applies
        v_ctx = StepContext(
            hlo_text=verify_hlo, flavor="speculative",
            compute_dtype="f32",
            expected_donated_params=v_expected,
            donated_param_info=v_pinfo,
            declared_donate_argnums=getattr(
                spec._verify, "_ds_donate_argnums", None),
            skip_rules={"recompile"})
        layout_findings.extend(run_rules(v_ctx, {"donation"}))
        layout_findings.extend(engine.recompile_findings())
        for f in layout_findings:
            f.details.setdefault("kv_layout", layout)
        findings.extend(layout_findings)
        ratio = draft_flops / full_flops if full_flops else None
        stats["layouts"][layout] = {
            "compile_counts": compile_counts,
            "completions": len(completions),
            "finish_reasons": sorted(
                c.finish_reason for c in completions),
            "speculative": spec.facts(),
            "draft_flops": draft_flops, "full_flops": full_flops,
            "draft_flops_ratio": ratio,
            "cache": engine.cache_facts(),
        }
        if layout == "paged":
            stats["layouts"][layout]["paging"] = sched.paging.facts()
        if kernel_ana is not None:
            stats["layouts"][layout]["kernels"] = kernel_ana.to_dict()
            stats["layouts"][layout]["kernels"]["expected_elision"] = \
                kernel_expected
        hlo_text = draft_hlo
    report = AuditReport(flavor="speculative", findings=findings)
    report.stats = _hlo_stats(hlo_text, StepContext(
        hlo_text=hlo_text, flavor="speculative"))
    report.stats.update(stats)
    report.hlo_text = hlo_text
    report.stats["audit_wall_s"] = round(time.perf_counter() - t0, 3)
    return report


def audit_disagg(rules=None, config_overrides=None):
    """Audit the disaggregated prefill/decode tiers (ISSUE 20).

    Builds one prefill-tier and one decode-tier engine over the SAME
    tiny model params but deliberately heterogeneous ``max_batch``
    (2 vs 3 — tiers size independently; the handoff contract pins only
    the paged geometry), drives a scripted mixed-length stream through
    the synchronous `inference/disagg.py:DisaggCoordinator`, then runs
    the rule catalog over the decode tier's post-stream program:

    - one-program-per-tier pins (``disagg_tier_counts``): after the
      whole stream the prefill tier's jit census must read
      ``{prefill: 1, decode: 0}`` and the decode tier's the inverse —
      the warmup-to-drain contract that makes tier capacity planning
      a pure host-side concern;
    - handoff geometry (``disagg_page_facts``): ``page_size`` /
      ``pages_per_row`` equal across tiers, because the handoff is a
      raw page copy keyed by the page table;
    - zero host-transfer ops in the decode tier's steady-state HLO
      (the handoff itself rides the store OUTSIDE the compiled
      programs) plus the standard paged-decode hygiene: donation of
      the paged pool, cache-dtype census, pool-geometry consistency.
    """
    import jax.numpy as jnp
    from deepspeed_tpu.inference.cache import cache_dtype_census
    from deepspeed_tpu.inference.disagg import DisaggCoordinator
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.scheduler import Request
    from deepspeed_tpu.models.gpt2 import GPT2LMHead, gpt2_tiny

    t0 = time.perf_counter()
    cfg = gpt2_tiny(n_embd=32, dtype=jnp.float32)
    model = GPT2LMHead(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    base = {"seq_buckets": (16, 32), "prefill_chunk": 4,
            "attention_block_k": 8, "kv_layout": "paged"}
    base.update(config_overrides or {})
    pre_engine = InferenceEngine(model, params, config=dict(
        base, max_batch=2, tier="prefill"))
    dec_engine = InferenceEngine(model, params, config=dict(
        base, max_batch=3, tier="decode"))
    coord = DisaggCoordinator([pre_engine], [dec_engine])
    rng = np.random.default_rng(0)
    # mixed-length stream across both buckets: short prompts, a
    # long-bucket prompt, and a mid-length one — every request crosses
    # the handoff (max_new_tokens > 1 keeps them off the
    # finish-at-prefill fast path)
    stream = [
        Request("r0", rng.integers(0, cfg.vocab_size, 3).tolist(),
                max_new_tokens=4),
        Request("r1", rng.integers(0, cfg.vocab_size, 20).tolist(),
                max_new_tokens=6),
        Request("r2", rng.integers(0, cfg.vocab_size, 6).tolist(),
                max_new_tokens=3),
        Request("r3", rng.integers(0, cfg.vocab_size, 12).tolist(),
                max_new_tokens=5),
    ]
    completions = coord.run(stream)
    hlo_text, expected, pinfo = _lower_step(
        dec_engine._decode, dec_engine.decode_lowering_args())
    tier_counts = {"prefill": pre_engine.compile_counts(),
                   "decode": dec_engine.compile_counts()}
    page_facts = {t: {"page_size": e.page_size,
                      "pages_per_row": e.pages_per_row,
                      "n_pages": e.n_pages, "max_seq": e.max_seq}
                  for t, e in (("prefill", pre_engine),
                               ("decode", dec_engine))}
    census = cache_dtype_census(dec_engine.cache)
    payload_shape = (dec_engine.spec.n_pages,
                     dec_engine.spec.page_size,
                     dec_engine.spec.n_head, dec_engine.spec.head_dim)
    ctx = StepContext(
        hlo_text=hlo_text, flavor="disagg",
        compute_dtype="f32",
        expected_donated_params=expected, donated_param_info=pinfo,
        declared_donate_argnums=getattr(
            dec_engine._decode, "_ds_donate_argnums", None),
        decode_compile_counts=dec_engine.compile_counts(),
        decode_kv_cache_dtype=dec_engine.kv_cache_dtype,
        decode_cache_census=census,
        decode_attention_impl=dec_engine.attention_impl,
        decode_cache_payload_shape=payload_shape,
        decode_platform=jax.devices()[0].platform,
        decode_kv_layout="paged",
        decode_page_facts=page_facts["decode"],
        disagg_tier_counts=tier_counts,
        disagg_page_facts=page_facts,
        skip_rules={"recompile"})
    findings = run_rules(ctx, rules)
    findings.extend(pre_engine.recompile_findings())
    findings.extend(dec_engine.recompile_findings())
    report = AuditReport(flavor="disagg", findings=findings)
    report.stats = _hlo_stats(hlo_text, ctx)
    report.hlo_text = hlo_text
    report.stats["tier_compile_counts"] = tier_counts
    report.stats["tier_page_facts"] = page_facts
    report.stats["tiers"] = coord.tier_stats()
    report.stats["completions"] = len(completions)
    report.stats["finish_reasons"] = sorted(
        c["finish_reason"] for c in completions)
    report.stats["cache"] = dec_engine.cache_facts()
    report.stats["audit_wall_s"] = round(time.perf_counter() - t0, 3)
    return report


def audit_flash_train(rules=None, batch=1, seq=128, n_head=2,
                      head_dim=128, block_q=64, block_k=64):
    """Audit the training flash-attention kernels (forward + both
    backward passes) with the sub-``pallas_call`` analyzer.

    Traces ``value_and_grad`` of a causal `ops/pallas/flash_attention.
    flash_attention` sum at a representative geometry, extracts the
    three Pallas kernels (fwd, dQ, dKV) and runs the kernel rule subset
    (:data:`KERNEL_RULES`) — there is no engine and no HLO here, so the
    step-level catalog doesn't apply. Stock blocks must come back
    zero-findings: lane dims 128-aligned, sublane dims 8-aligned for
    f32, VMEM working sets far under budget, and every output map
    constant in the innermost grid dim (the carried-accumulator idiom,
    not a grid-write race).
    """
    from deepspeed_tpu.analysis.kernels import analyze_kernels
    from deepspeed_tpu.ops.pallas import flash_attention

    t0 = time.perf_counter()
    shape = (batch, seq, n_head, head_dim)
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32) for kk in keys)

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=block_q,
                               block_k=block_k,
                               implementation="pallas").sum()

    fn = jax.value_and_grad(loss, argnums=(0, 1, 2))
    ana = analyze_kernels(fn, (q, k, v))
    ctx = StepContext(hlo_text="", flavor="flash_train",
                      kernel_analysis=ana,
                      skip_rules={"recompile"})
    findings = run_rules(ctx, set(rules) if rules is not None
                         else set(KERNEL_RULES))
    report = AuditReport(flavor="flash_train", findings=findings)
    report.stats = {"kernels": ana.to_dict(),
                    "geometry": {"batch": batch, "seq": seq,
                                 "n_head": n_head, "head_dim": head_dim,
                                 "block_q": block_q, "block_k": block_k},
                    "audit_wall_s": round(time.perf_counter() - t0, 3)}
    return report


def audit_kernel_flavors(rules=None):
    """The ``ds_tpu_audit --kernels`` sweep: every stock Pallas kernel
    path under the sub-``pallas_call`` analyzer.

    Covers the train flash-attention kernels (fwd/dQ/dKV), the decode
    flavor on BOTH kv layouts (ring clamp and paged clamp+gather index
    maps, each with its DMA-elision proof), and the speculative flavor
    (draft program, both layouts). Returns ``{name: AuditReport}``;
    stock kernels must come back zero-findings everywhere.
    """
    reports = {
        "flash_train": audit_flash_train(rules=rules),
        "decode_ring": audit_decode(rules=rules, kv_layout="ring",
                                    kernels=True),
        "decode_paged": audit_decode(rules=rules, kv_layout="paged",
                                     kernels=True),
        "speculative": audit_speculative(rules=rules, kernels=True),
    }
    for name, rep in reports.items():
        rep.flavor = name
    return reports


def audit_flavors(flavors=None, rules=None, steps=0,
                  config_overrides=None):
    """Build + audit toy engines for the stock flavors.

    Returns ``{flavor: AuditReport}`` in the order requested."""
    out = {}
    for flavor in flavors or STEP_FLAVORS:
        if flavor == "decode":
            # the serving flavor audits an InferenceEngine, not a
            # train-step engine — it has its own orchestrator.
            out[flavor] = audit_decode(rules=rules)
            continue
        if flavor == "speculative":
            out[flavor] = audit_speculative(rules=rules)
            continue
        if flavor == "disagg":
            out[flavor] = audit_disagg(rules=rules)
            continue
        engine, batch = build_flavor_engine(
            flavor, config_overrides=config_overrides)
        out[flavor] = audit_engine(engine, batch, rules=rules, steps=steps)
    return out
