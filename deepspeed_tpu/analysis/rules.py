"""Declarative audit rules over a compiled step's HLO.

Each rule is a pure function ``rule(ctx: StepContext) -> [Finding]`` —
it reads compile-time facts off the HLO text (via `analysis/hlo.py`)
and diffs them against what the engine configuration *promises*:
donated buffers actually alias outputs, bf16/fp16 runs don't leak fp32
onto the wire beyond the fp32-master design allowance, ZeRO stages stay
inside their per-stage byte budgets, nothing round-trips through the
host mid-step, and every collective-carrying loop has a statically
known trip count (else its volume cannot be accounted at all).

Rules return ``[]`` when not applicable (e.g. the dtype-hygiene rule on
a pure-fp32 run) so the orchestrator (`analysis/audit.py`) can run the
whole catalog over any step flavor. The allowances are deliberately
generous versions of the exact pins in ``tests/unit`` — tests pin exact
architecture numbers; rules catch order-of-magnitude regressions on
arbitrary user models.
"""

from dataclasses import dataclass, field, asdict

from deepspeed_tpu.analysis.hlo import (
    aliased_param_numbers,
    collective_bytes,
    collective_counts,
    collective_ops,
    fp8_value_counts,
    host_transfer_ops,
    while_loops,
)

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"
_SEV_RANK = {SEV_INFO: 0, SEV_WARNING: 1, SEV_ERROR: 2}


@dataclass
class Finding:
    rule: str
    severity: str
    message: str
    details: dict = field(default_factory=dict)

    def to_dict(self):
        return asdict(self)


@dataclass
class StepContext:
    """Everything a rule may diff the HLO against.

    ``expected_donated_params`` are HLO entry-parameter numbers (i.e.
    already mapped from ``donate_argnums`` through arg flattening and
    unused-arg pruning by the audit orchestrator); ``param_bytes`` is
    the fp32 master footprint the ZeRO budgets are expressed in.
    """
    hlo_text: str
    flavor: str = "custom"
    n_devices: int = 1
    compute_dtype: str = "f32"       # "bf16" | "f16" | "f32"
    zero_stage: int = 0
    comm_quantized: bool = False
    offload: bool = False
    pipeline: bool = False
    param_bytes: int = 0
    expected_donated_params: set = None
    donated_param_info: dict = field(default_factory=dict)
    declared_donate_argnums: tuple = None
    # Donated buffers smaller than this (scalar step counters, loss-scale
    # flags) are not an HBM concern; XLA may legitimately skip aliasing
    # them.
    min_donation_bytes: int = 64
    # tensor_parallel.overlap: when the config promises the latency-
    # hiding collective matmul, the overlap rule pins that the lowered
    # step actually carries the chunked ppermute rings.
    overlap_enabled: bool = False
    overlap_chunks: int = 1
    # fp8 (`ops/fp8.py` + the quantized collective wire): fp8_enabled
    # promises qdq matmuls (f8e4m3fn forward operands, f8e5m2 backward
    # cotangents in the lowered text); fp8_wire_dtype (a codec name from
    # `runtime/comm/codecs.py`) promises quantized collective payloads —
    # 1-byte wire buffers (the bitcast-packed u8 from `encode_wire`, or
    # raw s8/f8 elements) moving through the gather/ring family.
    fp8_enabled: bool = False
    fp8_wire_dtype: str = None
    # Explicit ZeRO-3 gather-on-use schedule (`zero/stage3.py:Zero3Plan`):
    # how many sharded leaves gather per use, the ring chunking, and the
    # largest single gathered leaf in compute-dtype bytes. gather_leaves
    # == 0 means no explicit schedule was declared (stages < 3, or the
    # legacy spec-sharded stage 3) and the schedule pins don't apply.
    zero3_gather_leaves: int = 0
    zero3_gather_chunks: int = 1
    zero3_max_gather_bytes: int = 0
    # Trace-time facts from the jaxpr front end (`analysis/jaxpr.py`);
    # None means the pass didn't run (HLO-only audits), [] means it ran
    # clean. The orchestrator fills these from the traced step.
    jaxpr_divergent: list = None     # check_divergent_collectives dicts
    jaxpr_unordered: list = None     # check_unordered_permutes dicts
    reshard_events: list = None      # propagate_partition_specs events
    replicated_leaves: list = None   # [{path, bytes, shape}] large fully-
    #                                  replicated state leaves
    collective_sites: list = None    # parallel.collectives SiteRecord
    #                                  dicts captured while tracing —
    #                                  source-level attribution for the
    #                                  permute-chain findings/stats
    # Conflicting-placement reshards below this are noise (tiny norms,
    # scalars); full replication below it is a deliberate choice the
    # ZeRO partitioner itself makes for small leaves.
    min_reshard_bytes: int = 1 << 20
    # Static peak-memory estimate (`analysis/hlo.py:estimate_peak_memory`
    # dict) and an optional explicit budget; 0 derives the per-ZeRO-stage
    # default from param_bytes.
    peak_memory: dict = None
    peak_budget_bytes: int = 0
    # Serving audit (`inference/engine.py`): decode_compile_counts is
    # the engine's {"prefill": n, "decode": n} AFTER a scripted stream
    # exercised admit/evict across >= 2 seq buckets — any program above
    # decode_expected_compiles means a shape leaked into a jit boundary
    # and the serving loop recompiled mid-stream. decode_cache_census
    # ({dtype: payload leaf count} from `cache_dtype_census`) plus the
    # configured decode_kv_cache_dtype pin cache-storage hygiene: one
    # payload dtype, and the codec's dtype when quantization is on.
    decode_compile_counts: dict = None
    decode_expected_compiles: int = 1
    decode_kv_cache_dtype: str = None
    decode_cache_census: dict = None
    # Flash-decode attention (`ops/pallas/flash_decode.py`):
    # decode_attention_impl names the engine's configured decode
    # attention ("dense" | "flash"; None = not a serving audit),
    # decode_cache_payload_shape is one layer's k/v buffer shape
    # (max_batch, max_seq, n_head, head_dim), and decode_platform is
    # the backend the audited program lowered for — the Pallas
    # custom-call pin only applies to real TPU lowerings (interpret
    # mode inlines the kernel as plain HLO).
    decode_attention_impl: str = None
    decode_cache_payload_shape: tuple = None
    decode_platform: str = None
    # Paged KV cache (`inference/paging.py`): decode_kv_layout names the
    # engine's cache layout ("ring" | "paged"; None = not a serving
    # audit). For a paged engine the page tables are fixed-shape int32
    # DATA inputs — allocator churn, prefix sharing and host-tier
    # parking are host-side bookkeeping that must never lower a host
    # transfer into the steady-state decode program (parking runs
    # OUTSIDE the compiled step, through `engine.gather_pages`).
    # decode_page_facts is the engine's `cache_facts()` geometry
    # (page_size / n_pages / pages_per_row / max_seq) for the
    # internal-consistency pins.
    decode_kv_layout: str = None
    decode_page_facts: dict = None
    # Speculative decoding (`inference/speculative.py`): spec_facts is
    # the decoder's `facts()` (k / draft_layers / n_layer and the
    # accept counters), spec_compile_counts the engine's full jit-cache
    # census {prefill, decode, draft, verify} after a scripted churn
    # stream — the pinned THREE-program contract, including decode == 0
    # (the plain decode program must never be entered while speculation
    # is on; one entry means the scheduler fell back mid-stream).
    # spec_draft_hlo / spec_verify_hlo are the compiled draft / verify
    # programs for host-transfer and payload pins; spec_draft_flops /
    # spec_full_flops are XLA cost-analysis flop counts for the
    # truncated draft step vs a same-shape full-depth step — their
    # ratio proves the truncation is real (~draft_layers/n_layer, not
    # ~1.0).
    spec_facts: dict = None
    spec_compile_counts: dict = None
    spec_draft_hlo: str = None
    spec_verify_hlo: str = None
    spec_draft_flops: float = 0.0
    spec_full_flops: float = 0.0
    # Disaggregated serving (`inference/disagg.py`):
    # disagg_tier_counts is {tier: compile_counts} after a scripted
    # stream ran through both tiers — the ONE-program-per-tier pin
    # (prefill tier {prefill: 1, decode: 0}, decode tier inverted; any
    # other census means a tier entered the other tier's program and
    # the whole point of the split is gone). disagg_page_facts is
    # {tier: cache_facts()} for the handoff-geometry pin: the KV
    # handoff is a raw page copy keyed by the page table, so
    # page_size/pages_per_row must match across tiers exactly.
    disagg_tier_counts: dict = None
    disagg_page_facts: dict = None
    # Pallas kernel analysis (`analysis/kernels.py`): kernel_analysis is
    # the step's `KernelAnalysis` (None = the sub-pallas_call pass did
    # not run; the kernel_* rules are inert). kernel_expected_elision is
    # the audit's *proof obligation* for the DMA-elision trick: the
    # dead-block fraction the clamped index maps MUST elide, computed
    # from the analysis scenario's positions
    # (`kernels.ring_dead_block_fraction`). None = no elision contract
    # (train kernels have no occupancy clamp to prove).
    kernel_analysis: object = None
    kernel_expected_elision: float = None
    skip_rules: set = field(default_factory=set)


def _fmt_bytes(n):
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024


def _slack(ctx):
    """Budget slack: 20% of the fp32 master footprint (floor 4KB).

    Generous on purpose — XLA may legitimately reduce a tied/shared
    parameter's gradient contributions separately before adding (e.g. a
    tied embedding pays its grad all-reduce twice), and scalars/norms
    ride along. The violations these rules exist for (a silent fp32
    upcast doubling wire bytes, a missing refresh gather, a whole extra
    param-sized exchange) overshoot 20% by construction."""
    return max(4096, int(0.2 * ctx.param_bytes))


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def rule_donation(ctx):
    """Declared ``donate_argnums`` must become real input/output aliases.

    The engine donates params/opt-state/device-state into each step so
    XLA updates them in place; a donation that fails to alias (or a
    dropped ``donate_argnums``) silently doubles that buffer's HBM."""
    if ctx.expected_donated_params is None:
        return []
    aliased = aliased_param_numbers(ctx.hlo_text)
    missing = []
    for p in sorted(ctx.expected_donated_params):
        info = ctx.donated_param_info.get(p, {})
        if info.get("bytes", ctx.min_donation_bytes) < ctx.min_donation_bytes:
            continue
        if p not in aliased:
            missing.append({"param": p, **info})
    if not missing:
        return []
    total = sum(m.get("bytes", 0) for m in missing)
    return [Finding(
        "donation", SEV_ERROR,
        f"{len(missing)} donated input buffer(s) totalling "
        f"{_fmt_bytes(total)} are not aliased into the step outputs — "
        f"un-donated params/opt-state live twice in HBM",
        {"missing_count": len(missing), "missing_bytes": total,
         "missing": missing[:16],
         "declared_donate_argnums":
             list(ctx.declared_donate_argnums or ()) or None,
         "aliased_params": len(aliased)})]


def rule_dtype_hygiene(ctx):
    """No fp32 on the wire beyond the fp32-master design allowance.

    In a bf16/fp16 run the *gradient* exchange legitimately rides fp32
    (fp32 master weights; `grad_epilogue` casts grads up before the
    all-reduce) and ZeRO-1/2's param-refresh all-gather ships the fp32
    masters — but ZeRO-3 gathers at compute dtype (cast-then-gather,
    `zero/sharding.py:make_param_caster`), and under comm_quantization
    the gradient all-reduce must have been replaced by the int8 exchange
    entirely. Anything above those allowances is a silent upcast paying
    2x wire bytes.

    fp8 runs need no extra allowance: the quantized wire packs its
    per-chunk f32 scales INSIDE the bitcast u8 buffers
    (`runtime/comm/codecs.py:encode_wire`), and the delayed-scaling
    amax state moves as tiny f32 max-reductions (a few histories of
    ``amax_history_len`` floats each) that sit well inside the 4KB
    slack floor."""
    low_precision = ctx.compute_dtype in ("bf16", "f16")
    if not low_precision and not ctx.comm_quantized:
        return []
    f32 = {}
    for op in collective_ops(ctx.hlo_text):
        b = op["dtype_bytes"].get("f32", 0) * op["multiplier"]
        if b:
            f32[op["op"]] = f32.get(op["op"], 0) + b
    m_bytes = ctx.param_bytes
    slack = _slack(ctx)
    findings = []

    reduce_f32 = f32.get("all-reduce", 0) + f32.get("reduce-scatter", 0)
    gather_f32 = f32.get("all-gather", 0)
    other_f32 = sum(b for op, b in f32.items()
                    if op not in ("all-reduce", "reduce-scatter",
                                  "all-gather"))

    if ctx.comm_quantized:
        # scales ride all-gather; the gradient all-reduce must be gone.
        if f32.get("all-reduce", 0) > 4096:
            findings.append(Finding(
                "dtype_hygiene", SEV_ERROR,
                f"comm_quantization is on but an fp32 all-reduce of "
                f"{_fmt_bytes(f32['all-reduce'])} remains — the gradient "
                f"sync was not replaced by the int8 exchange",
                {"f32_all_reduce_bytes": f32["all-reduce"]}))
        if not low_precision:
            return findings

    allow_reduce = m_bytes + slack
    allow_other = slack
    if ctx.zero_stage in (1, 2):
        allow_gather = m_bytes + slack      # fp32 master param refresh
    elif ctx.zero_stage >= 3:
        # Stage 3 gathers at compute dtype (cast-then-gather), but the
        # SPMD partitioner may sink the convert and re-widen the 16-bit
        # gather to f32 on the wire (the CPU backend does), and the
        # explicit path's backward re-gather doubles the pass count when
        # XLA doesn't CSE the remat. Budget the widened fwd+bwd envelope;
        # chunked rings are the same gathers as permutes, so they share
        # it through the "other" family.
        allow_gather = 2 * m_bytes + slack
        allow_other = 2 * m_bytes + slack
    else:
        # stage 0 has no param traffic.
        allow_gather = slack

    checks = [("all-reduce/reduce-scatter", reduce_f32, allow_reduce),
              ("all-gather", gather_f32, allow_gather),
              ("other collectives", other_f32, allow_other)]
    for name, got, allowed in checks:
        if got > allowed:
            findings.append(Finding(
                "dtype_hygiene", SEV_ERROR,
                f"fp32 {name} traffic of {_fmt_bytes(got)} exceeds the "
                f"{ctx.compute_dtype} run's allowance of "
                f"{_fmt_bytes(allowed)} — a silent upcast is paying 2x "
                f"wire bytes",
                {"family": name, "f32_bytes": got, "allowed_bytes": allowed,
                 "zero_stage": ctx.zero_stage,
                 "compute_dtype": ctx.compute_dtype}))
    return findings


def rule_zero_budget(ctx):
    """Per-stage ZeRO collective byte ceilings (output-bytes basis).

    Generalizes the pinned proofs of ``test_zero_comm_volume.py`` into
    ceilings any model can be checked against: stage 0 moves one
    gradient exchange and NO param traffic; stages 1/2 add exactly one
    param-sized refresh gather; stage 3's total stays within the ZeRO
    paper's 1.5x-of-DP envelope. M = fp32 param bytes."""
    if ctx.param_bytes <= 0 or ctx.comm_quantized or ctx.pipeline:
        return []
    v = collective_bytes(ctx.hlo_text)
    m_bytes = ctx.param_bytes
    slack = _slack(ctx)
    ar = v.get("all-reduce", 0) + v.get("reduce-scatter", 0)
    ag = v.get("all-gather", 0)
    findings = []

    def over(name, got, allowed, extra=None):
        findings.append(Finding(
            "zero_budget", SEV_ERROR,
            f"stage-{ctx.zero_stage} {name} volume {_fmt_bytes(got)} "
            f"exceeds the budget {_fmt_bytes(allowed)} "
            f"(M = {_fmt_bytes(m_bytes)})",
            dict({"got_bytes": got, "allowed_bytes": allowed,
                  "param_bytes": m_bytes, "volumes": v}, **(extra or {}))))

    if ctx.offload or ctx.zero_stage == 0:
        if ar > m_bytes + slack:
            over("gradient exchange (all-reduce)", ar, m_bytes + slack)
        if ag > slack:
            over("all-gather", ag, slack,
                 {"note": "plain DP / offload grad step has no param "
                          "refresh gather"})
    elif ctx.zero_stage in (1, 2):
        if ar > m_bytes + slack:
            over("gradient exchange (all-reduce)", ar, m_bytes + slack)
        if ag > m_bytes + slack:
            over("param refresh (all-gather)", ag, m_bytes + slack)
        if ar < m_bytes - slack:
            findings.append(Finding(
                "zero_budget", SEV_WARNING,
                f"stage-{ctx.zero_stage} gradient exchange "
                f"{_fmt_bytes(ar)} is below M-{_fmt_bytes(slack)} — "
                f"gradient sync may be missing",
                {"got_bytes": ar, "param_bytes": m_bytes}))
    else:  # stage >= 3
        # Total envelope: forward per-use gathers (one param-sized pass,
        # f32-widened worst case on backends that sink the 16-bit cast
        # through the gather) + the backward re-gather (a second pass
        # when XLA doesn't CSE the remat's recompute back into the
        # forward's) + the fp32 gradient exchange — the ZeRO paper's 3Ψ
        # vs plain DP's 2Ψ, i.e. the 1.5x envelope, measured here at the
        # widened worst case.
        total = v.get("total", 0)
        allowed = int(3.2 * m_bytes) + 2 * slack
        if total > allowed:
            over("total collective", total, allowed)
        if ctx.zero3_gather_leaves > 0:
            # An explicit gather-on-use schedule was declared: pin it.
            # (a) No up-front/monolithic all-gather — no single gather
            # op may move more than the largest declared leaf (the
            # schedule gathers layer-by-layer; one op carrying the whole
            # param tree is exactly the regression it exists to prevent).
            per_leaf = 2 * ctx.zero3_max_gather_bytes + slack
            for op in collective_ops(ctx.hlo_text):
                if op["op"] != "all-gather":
                    continue
                b = sum(op["dtype_bytes"].values())
                if b > per_leaf:
                    findings.append(Finding(
                        "zero_budget", SEV_ERROR,
                        f"stage-{ctx.zero_stage} all-gather of "
                        f"{_fmt_bytes(b)} exceeds the largest declared "
                        f"per-leaf gather allowance {_fmt_bytes(per_leaf)}"
                        f" — an up-front full-param gather defeats the "
                        f"gather-on-use schedule",
                        {"got_bytes": b, "allowed_bytes": per_leaf,
                         "computation": op.get("computation"),
                         "gather_leaves": ctx.zero3_gather_leaves,
                         "max_gather_bytes": ctx.zero3_max_gather_bytes}))
            # (b) Per-layer gather counts: every sharded leaf must
            # gather through its own op (all-gather, or ppermute ring
            # hops when chunked) — fewer gather-family ops than leaves
            # means leaves were coalesced into a bulk gather.
            counts = collective_counts(ctx.hlo_text)
            gather_ops = counts.get("all-gather", 0) + \
                counts.get("collective-permute", 0)
            if gather_ops < ctx.zero3_gather_leaves:
                findings.append(Finding(
                    "zero_budget", SEV_ERROR,
                    f"stage-{ctx.zero_stage} step executes only "
                    f"{gather_ops} gather-family op(s) for "
                    f"{ctx.zero3_gather_leaves} sharded leaves — the "
                    f"per-layer gather schedule did not reach the "
                    f"lowered program",
                    {"gather_ops": gather_ops,
                     "gather_leaves": ctx.zero3_gather_leaves,
                     "counts": counts}))
    return findings


def rule_host_transfer(ctx):
    """No host round-trips inside a compiled step.

    Infeed/outfeed, ``is_host_transfer=true`` sends/recvs, and Python
    host-callback custom-calls each force a device/host sync mid-step —
    the async dispatch pipeline stalls every step."""
    hits = host_transfer_ops(ctx.hlo_text)
    if not hits:
        return []
    kinds = sorted({h["kind"] for h in hits})
    return [Finding(
        "host_transfer", SEV_ERROR,
        f"{len(hits)} host transfer op(s) inside the compiled step "
        f"({', '.join(kinds)}) — each forces a mid-step host sync",
        {"count": len(hits), "kinds": kinds,
         "ops": [h["line"][:200] for h in hits[:8]]})]


def rule_trip_count(ctx):
    """Every collective-carrying loop must have a static trip count.

    Without one the loop's collective volume cannot be accounted (the
    historical flat-count limitation) and none of the byte-budget rules
    can be trusted for this program."""
    unknown = [l for l in while_loops(ctx.hlo_text)
               if l["has_collectives"] and l["trip_count"] is None]
    if not unknown:
        return []
    return [Finding(
        "trip_count", SEV_WARNING,
        f"{len(unknown)} while loop(s) carry collectives but have no "
        f"statically known trip count — their wire volume is "
        f"under-accounted (counted once, not per iteration)",
        {"loops": [{"body": l["body"], "parent": l["parent"]}
                   for l in unknown]})]


def rule_overlap(ctx):
    """The promised latency-hiding collective matmul must be in the HLO.

    With ``tensor_parallel.overlap`` enabled on a pipeline step, the
    rewired manual-TP sites replace their monolithic blocking collectives
    with chunked ``collective-permute`` rings — so the lowered program
    must execute at least ``chunks - 1`` collective-permutes (the 1F1B
    stage transfers alone already permute; the ring chunks add more),
    and the in-loop (per-tick) ``all-reduce`` count must be ZERO: any
    all-reduce executing more than once per step means a rewired site
    regressed to the blocking form. (The legitimate grad/loss psums run
    once, after the tick scan — multiplier 1.)

    Separately (not pipeline-gated): an explicit ZeRO-3 schedule with
    ``gather_chunks > 1`` promises each sharded leaf gathers as
    ``chunks`` ppermute ring stripes (`zero/stage3.py`) — the lowered
    step must carry at least ``leaves x (chunks - 1)`` collective-
    permutes, else the ring rewiring silently fell back to monolithic
    all-gathers."""
    findings = []
    counts = collective_counts(ctx.hlo_text)
    if ctx.overlap_enabled and ctx.pipeline:
        permutes = counts.get("collective-permute", 0)
        need = max(1, ctx.overlap_chunks - 1)
        if permutes < need:
            findings.append(Finding(
                "overlap", SEV_ERROR,
                f"tensor_parallel.overlap promises chunked ppermute rings "
                f"(chunks={ctx.overlap_chunks}) but the step executes only "
                f"{permutes} collective-permute(s) (< {need}) — the overlap "
                f"rewiring did not reach the lowered program",
                {"collective_permutes": permutes, "required": need,
                 "chunks": ctx.overlap_chunks, "counts": counts}))
        if ctx.overlap_chunks > 1:
            in_loop = [op for op in collective_ops(ctx.hlo_text)
                       if op["op"] == "all-reduce" and op["multiplier"] > 1]
            if in_loop:
                total = sum(op["multiplier"] for op in in_loop)
                findings.append(Finding(
                    "overlap", SEV_ERROR,
                    f"{len(in_loop)} all-reduce op(s) execute inside the "
                    f"pipeline tick loop ({total} executions/step) — a "
                    f"rewired row-parallel/combine site regressed to the "
                    f"monolithic blocking collective",
                    {"in_loop_all_reduces": len(in_loop),
                     "executions_per_step": total,
                     "computations": sorted({op["computation"] or ""
                                             for op in in_loop})}))
    if ctx.zero_stage >= 3 and ctx.zero3_gather_leaves > 0 and \
            ctx.zero3_gather_chunks > 1 and ctx.n_devices > 1:
        permutes = counts.get("collective-permute", 0)
        need = max(1, ctx.zero3_gather_leaves
                   * (ctx.zero3_gather_chunks - 1))
        if permutes < need:
            findings.append(Finding(
                "overlap", SEV_ERROR,
                f"zero_optimization.gather_chunks="
                f"{ctx.zero3_gather_chunks} promises ppermute ring "
                f"stripes for {ctx.zero3_gather_leaves} gathered leaves "
                f"but the step executes only {permutes} "
                f"collective-permute(s) (< {need}) — the ring gather "
                f"schedule did not reach the lowered program",
                {"collective_permutes": permutes, "required": need,
                 "gather_chunks": ctx.zero3_gather_chunks,
                 "gather_leaves": ctx.zero3_gather_leaves,
                 "counts": counts}))
    return findings


def rule_deadlock(ctx):
    """No collective may execute divergently, and concurrent permutes
    must be dep-chained.

    Both facts come from the traced jaxpr (`analysis/jaxpr.py`), i.e.
    they are proven before the program ever runs — which matters because
    the failure mode being detected is a hang, not an exception. A
    collective inside control flow that branches on a device-varying
    value (anything derived from ``lax.axis_index``) strands part of its
    rendezvous on the other branch: fatal always for
    ``ppermute``/collective-permute (global rendezvous — the PR 5
    stage-divergent pipeline deadlock), fatal for grouped collectives
    when the divergence splits their own axis. Separately, two
    ``ppermute``s with no dataflow edge between them can be in flight
    simultaneously and split the in-process runtime's rendezvous — the
    invariant ``parallel.collectives.barrier_after`` exists to maintain,
    checked here instead of assumed."""
    findings = []
    for d in ctx.jaxpr_divergent or ():
        findings.append(Finding(
            "deadlock", SEV_ERROR, d["message"],
            {"primitive": d.get("primitive"),
             "axes": list(d.get("axes", ())),
             "divergent_axes": list(d.get("divergent_axes", ())),
             "path": list(d.get("path", ()))}))
    for d in ctx.jaxpr_unordered or ():
        findings.append(Finding(
            "deadlock", SEV_ERROR, d["message"],
            {"kind": "unordered_permutes",
             "path": list(d.get("path", ())),
             "eqns": list(d.get("eqns", ()))}))
    for s in ctx.collective_sites or ():
        # source-level confession: an emitter declared it skipped the
        # dep-chain (parallel.collectives SiteRecord.chained=False).
        if s.get("primitive") == "ppermute" and not s.get("chained", True):
            findings.append(Finding(
                "deadlock", SEV_ERROR,
                f"collective site {s.get('site')!r} emits ppermutes over "
                f"axis {s.get('axis')!r} outside the barrier_after "
                f"dep-chain: concurrent in-flight permutes split the "
                f"global rendezvous",
                {"kind": "unchained_site", "site": dict(s)}))
    return findings


def rule_resharding(ctx):
    """Sharding-flow hygiene: no accidental replication, no unattributed
    reshards.

    From the PartitionSpec propagation over the traced jaxpr: operands
    meeting with *conflicting* placements force a compiler-inserted
    reshard (an all-gather + reslice) that no declared overlap/gather
    site accounts for — flagged per conflict above
    ``min_reshard_bytes``. Separately, a ZeRO run (stage >= 1) whose
    optimizer state contains large fully-replicated leaves is paying
    stage-0 memory while claiming otherwise — the partitioner
    (`zero/sharding.py`) legitimately replicates only small or
    non-divisible leaves, so big replicated ones mean the spec never
    attached. (ZeRO-1/2's param-refresh all-gathers are GSPMD-implicit
    sharding declarations, not jaxpr eqns, so attribution here is
    config-driven: the refresh allowance lives in ``rule_zero_budget``'s
    byte ceilings, while this rule polices placements.)

    An explicit gather-on-use stage-3 run (`zero/stage3.py`) *declares*
    its gather/re-shard traffic through ``SiteRecord``s (sites
    ``zero3_gather`` / ``zero3_reshard``): conflict events no larger
    than the declared per-leaf gather are attributed to that schedule
    and exempted. A stage-3 run whose trace registered NO zero3 sites
    gets no exemption — an unregistered gather still fires here."""
    findings = []
    big = [e for e in ctx.reshard_events or ()
           if e.get("bytes", 0) >= ctx.min_reshard_bytes]
    if big and ctx.zero_stage >= 3 and ctx.zero3_max_gather_bytes > 0:
        zero3_sites = [s for s in ctx.collective_sites or ()
                       if str(s.get("site", "")).startswith("zero3_")]
        if zero3_sites:
            allow = 2 * ctx.zero3_max_gather_bytes + 4096
            big = [e for e in big if e.get("bytes", 0) > allow]
    if big:
        total = sum(e["bytes"] for e in big)
        findings.append(Finding(
            "resharding", SEV_WARNING,
            f"{len(big)} operand join(s) with conflicting "
            f"PartitionSpecs (largest {_fmt_bytes(max(e['bytes'] for e in big))}, "
            f"total {_fmt_bytes(total)}) force compiler-inserted "
            f"reshards not attributable to any declared gather site",
            {"events": big[:8], "total_bytes": total}))
    if ctx.zero_stage >= 1 and ctx.n_devices > 1:
        rep = [l for l in ctx.replicated_leaves or ()
               if l.get("bytes", 0) >= ctx.min_reshard_bytes]
        if rep:
            total = sum(l["bytes"] for l in rep)
            findings.append(Finding(
                "resharding", SEV_ERROR,
                f"stage-{ctx.zero_stage} run holds {len(rep)} large "
                f"fully-replicated optimizer-state leaves "
                f"({_fmt_bytes(total)}) — the ZeRO partition spec never "
                f"attached; every device pays stage-0 memory",
                {"leaves": rep[:8], "total_bytes": total}))
    return findings


def rule_peak_memory(ctx):
    """Static peak device memory must fit the per-ZeRO-stage budget.

    The liveness estimate (`analysis/hlo.py:estimate_peak_memory`) is
    checked against an explicit ``peak_budget_bytes`` when configured,
    else a generous per-stage formula in units of M (fp32 master bytes):
    params (M) + optimizer state (3M, sharded /N under ZeRO >= 1, 0 on
    device under offload) + 3M of gradients/activations headroom. Toy
    flavors sit near 50% of this; the rule exists to catch
    order-of-magnitude regressions (a lost donation doubling state, a
    replicated optimizer) on real models — exact orderings are pinned
    by tests, not here."""
    est = ctx.peak_memory
    if not est or ctx.param_bytes <= 0:
        return []
    m_bytes = ctx.param_bytes
    budget = ctx.peak_budget_bytes
    if not budget:
        n = max(ctx.n_devices, 1)
        if ctx.offload:
            opt_m = 0.0
        elif ctx.zero_stage >= 1:
            opt_m = 3.0 / n
        else:
            opt_m = 3.0
        budget = int(m_bytes * (1.0 + opt_m + 3.0)) + 2 * _slack(ctx)
    peak = est.get("peak_bytes", 0)
    if peak <= budget:
        return []
    return [Finding(
        "peak_memory", SEV_ERROR,
        f"static peak-memory estimate {_fmt_bytes(peak)} exceeds the "
        f"stage-{ctx.zero_stage} budget {_fmt_bytes(budget)} "
        f"(M = {_fmt_bytes(m_bytes)}; args "
        f"{_fmt_bytes(est.get('parameter_bytes', 0))} + liveness peak "
        f"{_fmt_bytes(est.get('temp_peak_bytes', 0))})",
        {"peak_bytes": peak, "budget_bytes": budget,
         "parameter_bytes": est.get("parameter_bytes", 0),
         "temp_peak_bytes": est.get("temp_peak_bytes", 0),
         "donated_output_bytes": est.get("donated_output_bytes", 0),
         "zero_stage": ctx.zero_stage, "param_bytes": m_bytes})]


def rule_fp8(ctx):
    """The promised fp8 compute and quantized wire must be in the HLO.

    ``fp8_enabled`` promises qdq matmuls: the lowered step must carry
    ``f8e4m3fn``-typed values (forward-operand quantizes — on CPU the
    explicit converts next to the f32 dot, on TPU the operands of the
    fused native fp8 GEMM) AND ``f8e5m2``-typed values (the backward
    cotangent quantizes); either missing means the fp8 rewiring was
    silently dropped — paying bf16/fp32 compute while claiming fp8.

    ``fp8_wire_dtype`` promises quantized collective payloads: at least
    one collective must move a 1-byte element type (the bitcast-packed
    ``u8`` wire buffer from `runtime/comm/codecs.py:encode_wire`, or
    raw ``s8``/fp8 elements). Zero 1-byte collective bytes means every
    ring/gather still ships full precision."""
    if not ctx.fp8_enabled and not ctx.fp8_wire_dtype:
        return []
    findings = []
    if ctx.fp8_enabled:
        counts = fp8_value_counts(ctx.hlo_text)
        e4 = sum(n for dt, n in counts.items() if dt.startswith("f8e4m3"))
        e5 = counts.get("f8e5m2", 0)
        if e4 == 0:
            findings.append(Finding(
                "fp8", SEV_ERROR,
                "fp8 is enabled but the lowered step carries no "
                "f8e4m3fn-typed values — no forward operand is "
                "quantized; the fp8 matmul rewiring did not reach the "
                "compiled program",
                {"fp8_value_counts": counts}))
        if e5 == 0:
            findings.append(Finding(
                "fp8", SEV_ERROR,
                "fp8 is enabled but the lowered step carries no "
                "f8e5m2-typed values — backward cotangents are not "
                "quantized (out_qdq missing from the backward)",
                {"fp8_value_counts": counts}))
    if ctx.fp8_wire_dtype:
        cb = collective_bytes(ctx.hlo_text, by_dtype=True)
        wire = 0
        for op, d in cb.items():
            if op == "total":
                continue
            wire += sum(b for dt, b in d.items()
                        if dt in ("u8", "s8") or dt.startswith("f8"))
        if wire == 0:
            findings.append(Finding(
                "fp8", SEV_ERROR,
                f"fp8 wire_dtype={ctx.fp8_wire_dtype!r} promises "
                f"quantized collective payloads but no collective moves "
                f"a 1-byte element type — every gather/ring still ships "
                f"full precision",
                {"wire_dtype": ctx.fp8_wire_dtype,
                 "collective_bytes_by_dtype":
                     {op: dict(d) for op, d in cb.items()
                      if op != "total"}}))
    return findings


def rule_decode(ctx):
    """The serving loop's recompile contract and cache-dtype hygiene.

    The decode engine compiles exactly two programs (chunked prefill +
    decode) and reuses them for the whole serve; admission, eviction
    and seq buckets are host-side bookkeeping that must never reach a
    jit boundary. ``decode_compile_counts`` is the engine's jit-cache
    census after a stream crossed bucket sizes — growth past
    ``decode_expected_compiles`` is the mid-stream recompile the whole
    design exists to prevent (every extra entry stalls live requests
    for a full XLA compile).

    Cache hygiene: the KV cache's payload leaves must store ONE dtype,
    and when ``kv_cache_dtype`` names a codec it must be that codec's
    dtype — a mixed or full-precision census means some layer's cache
    silently skipped quantization and the promised HBM saving is gone.

    Paged layout (``decode_kv_layout == "paged"``): the page tables are
    fixed-shape device data — steady-state decode must lower ZERO host
    transfer ops (a page gather routed through infeed/outfeed or a host
    callback stalls every step; host-tier parking runs outside the
    compiled programs), and the pool geometry must be internally
    consistent (page 0 is the reserved trash page, so ``n_pages >= 2``;
    ``pages_per_row * page_size`` must cover ``max_seq`` exactly, else
    some row positions have no page-table entry and decode reads the
    trash page as live KV).

    Disaggregated tiers (``disagg_tier_counts``): each tier pins
    exactly ONE compiled program — its own — warmup-to-drain; an entry
    in the other tier's jit cache means the tier boundary leaked (a
    prefill worker decoding, or vice versa). And because the handoff
    is a raw page copy keyed by the page table, both tiers must share
    ``page_size``/``pages_per_row`` exactly (``disagg_page_facts``) —
    a mismatch scatters prefilled KV into the wrong pool offsets.
    """
    if ctx.decode_compile_counts is None and \
            ctx.decode_cache_census is None and \
            ctx.decode_kv_layout is None and \
            ctx.disagg_tier_counts is None:
        return []
    findings = []
    if ctx.decode_kv_layout == "paged":
        hits = host_transfer_ops(ctx.hlo_text) if ctx.hlo_text else []
        if hits:
            kinds = sorted({h["kind"] for h in hits})
            findings.append(Finding(
                "decode", SEV_ERROR,
                f"paged decode program lowers {len(hits)} host transfer "
                f"op(s) ({', '.join(kinds)}) — page-table gathers must "
                f"stay on device; a host round-trip in steady-state "
                f"decode stalls every step",
                {"count": len(hits), "kinds": kinds,
                 "ops": [h["line"][:200] for h in hits[:8]]}))
        pf = ctx.decode_page_facts or {}
        ps = pf.get("page_size", 0)
        n_pg = pf.get("n_pages", 0)
        ppr = pf.get("pages_per_row", 0)
        max_seq = pf.get("max_seq", 0)
        if pf:
            if ps < 1 or n_pg < 2 or ppr < 1:
                findings.append(Finding(
                    "decode", SEV_ERROR,
                    f"paged cache geometry is degenerate (page_size="
                    f"{ps}, n_pages={n_pg}, pages_per_row={ppr}) — the "
                    f"pool needs >= 2 pages (page 0 is the reserved "
                    f"trash page) and a positive page size",
                    {"page_facts": dict(pf)}))
            elif max_seq and ppr * ps != max_seq:
                findings.append(Finding(
                    "decode", SEV_ERROR,
                    f"paged cache geometry mismatch: pages_per_row="
                    f"{ppr} x page_size={ps} = {ppr * ps} does not "
                    f"cover max_seq={max_seq} — positions past the "
                    f"table read the trash page as live KV",
                    {"page_facts": dict(pf)}))
    if ctx.disagg_tier_counts:
        pins = {"prefill": {"prefill": 1, "decode": 0},
                "decode": {"prefill": 0, "decode": 1}}
        for tier, counts in sorted(ctx.disagg_tier_counts.items()):
            want = pins.get(tier)
            if want is None:
                continue
            got = {p: int((counts or {}).get(p) or 0)
                   for p in ("prefill", "decode")}
            if got != want:
                findings.append(Finding(
                    "decode", SEV_ERROR,
                    f"disaggregated {tier} tier holds compile counts "
                    f"{got} (expected {want}) — each tier pins exactly "
                    f"one compiled program, its own, warmup-to-drain; "
                    f"any entry in the other tier's program means the "
                    f"tier boundary leaked",
                    {"tier": tier, "counts": got, "expected": want}))
    dpf = ctx.disagg_page_facts
    if dpf and "prefill" in dpf and "decode" in dpf:
        for key in ("page_size", "pages_per_row"):
            a = (dpf.get("prefill") or {}).get(key)
            b = (dpf.get("decode") or {}).get(key)
            if a != b:
                findings.append(Finding(
                    "decode", SEV_ERROR,
                    f"handoff geometry mismatch: prefill tier {key}="
                    f"{a} vs decode tier {key}={b} — the KV handoff "
                    f"is a raw page copy keyed by the page table, so "
                    f"both tiers must share the paged geometry "
                    f"exactly",
                    {"key": key, "prefill": a, "decode": b}))
    for prog, n in sorted((ctx.decode_compile_counts or {}).items()):
        if n is not None and n > ctx.decode_expected_compiles:
            findings.append(Finding(
                "decode", SEV_ERROR,
                f"serving {prog} program accumulated {n} jit cache "
                f"entries (expected {ctx.decode_expected_compiles}) — "
                f"a shape or dtype leaked into the compiled boundary "
                f"and the decode loop recompiled mid-stream",
                {"program": prog, "cache_size": n,
                 "expected": ctx.decode_expected_compiles}))
    census = ctx.decode_cache_census
    if census:
        if len(census) > 1:
            findings.append(Finding(
                "decode", SEV_ERROR,
                f"KV cache payload leaves store mixed dtypes "
                f"{sorted(census)} — every layer's cache must share one "
                f"storage dtype",
                {"census": dict(census),
                 "kv_cache_dtype": ctx.decode_kv_cache_dtype}))
        from deepspeed_tpu.runtime.comm.codecs import CODECS
        codec = CODECS.get(ctx.decode_kv_cache_dtype)
        if codec is not None:
            import jax.numpy as jnp
            want = str(jnp.dtype(codec.dtype))
            stray = sorted(dt for dt in census if dt != want)
            if stray:
                findings.append(Finding(
                    "decode", SEV_ERROR,
                    f"kv_cache_dtype={ctx.decode_kv_cache_dtype!r} "
                    f"promises {want} cache storage but payload leaves "
                    f"store {stray} — quantization silently skipped; "
                    f"the promised KV HBM saving is not happening",
                    {"census": dict(census), "expected_dtype": want,
                     "kv_cache_dtype": ctx.decode_kv_cache_dtype}))
    return findings


def rule_flash_decode(ctx):
    """Flash decode actually deleted the dense attention work.

    When the engine promises ``attention_impl="flash"`` the compiled
    decode program must show it, not just route through a differently-
    named Python function:

    - on TPU the Pallas kernel lowers to a ``custom-call`` — its
      absence means the kernel silently fell back to something XLA
      made up (interpret mode off-TPU inlines the kernel as plain HLO,
      so that pin is platform-gated);
    - NO dot may touch a full cache-payload-shaped array
      (`analysis/hlo.py:payload_shaped_dots`): one surviving
      ``[max_batch, max_seq, n_head, head_dim]`` contraction means the
      dense softmax is still running and the O(max_seq) HBM traffic
      the kernel exists to delete is still being paid;
    - with a quantized cache, NO f32 value may be cache-payload-shaped
      (`payload_shaped_values`): such a value is the dense path's
      dequantized HBM copy — flash dequantizes in-register per block.
    """
    if ctx.decode_attention_impl != "flash":
        return []
    findings = []
    if ctx.decode_platform == "tpu" and "custom-call" not in ctx.hlo_text:
        findings.append(Finding(
            "flash_decode", SEV_ERROR,
            "attention_impl='flash' on TPU but the decode program "
            "contains no custom-call — the Pallas flash-decode kernel "
            "never made it into the lowering",
            {"platform": ctx.decode_platform}))
    payload = ctx.decode_cache_payload_shape
    if payload:
        from deepspeed_tpu.analysis.hlo import (payload_shaped_dots,
                                                payload_shaped_values)
        dots = payload_shaped_dots(ctx.hlo_text, payload)
        if dots:
            findings.append(Finding(
                "flash_decode", SEV_ERROR,
                f"attention_impl='flash' but {len(dots)} dot(s) still "
                f"contract over the full cache payload shape "
                f"{tuple(payload)} — the dense attention softmax "
                f"survived the rewrite",
                {"payload_shape": tuple(payload),
                 "dots": dots[:8]}))
        from deepspeed_tpu.runtime.comm.codecs import CODECS
        if ctx.decode_kv_cache_dtype in CODECS:
            n = payload_shaped_values(ctx.hlo_text, "f32", payload)
            if n:
                findings.append(Finding(
                    "flash_decode", SEV_ERROR,
                    f"quantized KV cache "
                    f"({ctx.decode_kv_cache_dtype!r}) but the decode "
                    f"program materializes {n} f32 cache-payload-"
                    f"shaped value(s) — a full-precision dequantized "
                    f"cache copy is being written to HBM",
                    {"payload_shape": tuple(payload),
                     "f32_payload_values": n,
                     "kv_cache_dtype": ctx.decode_kv_cache_dtype}))
    return findings


def rule_speculative(ctx):
    """Self-speculative decoding's pinned contracts.

    Program-count contract: a speculative serve compiles exactly THREE
    programs — prefill, draft, verify — and the plain decode program
    stays at ZERO jit-cache entries. One decode entry means the
    scheduler silently fell back to token-at-a-time mid-stream (the
    speedup is gone and nobody noticed); draft/verify above 1 means a
    shape (draft window, batch, bucket) leaked into a jit boundary.

    Truncation contract: the draft program must actually run only
    ``draft_layers`` of ``n_layer`` blocks. XLA cost-analysis flops for
    the draft step vs a same-shape full-depth step prove it — the
    ratio must sit near draft_layers/n_layer, not near 1.0 (a ratio
    near 1.0 means the truncation knob never reached the lowering and
    the "draft" pays full-model cost for approximate tokens).

    Accept-loop invariants: every verify round emits the correction /
    bonus token even when all drafts miss, so ``mean_accepted`` (tokens
    emitted per row-round) is >= 1.0 BY CONSTRUCTION — below 1.0 the
    accept machinery is dropping tokens. ``draft_efficiency`` is a
    fraction of drafted tokens and must stay within [0, 1].

    Paged layout: draft and verify are steady-state programs — both
    must lower zero host-transfer ops, same as plain decode. Flash
    draft (T=1) on TPU must carry the Pallas custom-call and must not
    contract over the full cache payload shape (verify always runs
    dense full-depth; its payload dots are expected).
    """
    if ctx.spec_facts is None:
        return []
    findings = []
    facts = ctx.spec_facts
    expected = {"prefill": 1, "decode": 0, "draft": 1, "verify": 1}
    for prog, want in sorted(expected.items()):
        n = (ctx.spec_compile_counts or {}).get(prog)
        if n is None or n == want:
            continue
        if prog == "decode":
            msg = (f"speculative serve entered the plain decode "
                   f"program {n} time(s) — speculation silently fell "
                   f"back to token-at-a-time decoding mid-stream")
        else:
            msg = (f"speculative {prog} program accumulated {n} jit "
                   f"cache entries (expected {want}) — a shape or "
                   f"dtype leaked into the compiled boundary")
        findings.append(Finding(
            "speculative", SEV_ERROR, msg,
            {"program": prog, "cache_size": n, "expected": want,
             "compile_counts": dict(ctx.spec_compile_counts or {})}))
    dl = facts.get("draft_layers", 0)
    nl = facts.get("n_layer", 0)
    if not 0 < dl < nl:
        findings.append(Finding(
            "speculative", SEV_ERROR,
            f"degenerate draft depth draft_layers={dl} of n_layer={nl} "
            f"reached the engine — the builder must disable "
            f"speculation (2-program fallback) instead of drafting at "
            f"full depth",
            {"facts": dict(facts)}))
    if ctx.spec_full_flops and ctx.spec_draft_flops:
        ratio = ctx.spec_draft_flops / ctx.spec_full_flops
        # non-layer work (embeddings, ln_f, lm_head) is shared, so the
        # honest ratio lands between draft_layers/n_layer and 1;
        # flagging past the midpoint catches the failure mode this pin
        # exists for (truncation never lowered -> ratio ~= 1.0)
        bound = (dl / nl + 1.0) / 2.0 if nl else 1.0
        if ratio > bound:
            findings.append(Finding(
                "speculative", SEV_ERROR,
                f"draft step costs {ratio:.2f}x the full-depth step "
                f"(expected ~{dl}/{nl} = {dl / nl if nl else 0:.2f}, "
                f"bound {bound:.2f}) — the layer truncation never "
                f"reached the lowering and the draft pays full-model "
                f"flops",
                {"draft_flops": ctx.spec_draft_flops,
                 "full_flops": ctx.spec_full_flops,
                 "ratio": ratio, "bound": bound}))
    rounds = facts.get("row_rounds", 0)
    mean_acc = facts.get("mean_accepted", 0.0)
    if rounds and mean_acc < 1.0:
        findings.append(Finding(
            "speculative", SEV_ERROR,
            f"mean accepted tokens/round is {mean_acc:.3f} over "
            f"{rounds} row-round(s) — every verify emits at least the "
            f"correction token, so < 1.0 means the accept loop is "
            f"dropping tokens",
            {"facts": dict(facts)}))
    eff = facts.get("draft_efficiency", 0.0)
    if not 0.0 <= eff <= 1.0:
        findings.append(Finding(
            "speculative", SEV_ERROR,
            f"draft_efficiency {eff:.3f} outside [0, 1] — accepted "
            f"draft count exceeds drafted count; the accept gather is "
            f"reading past the draft window",
            {"facts": dict(facts)}))
    if ctx.decode_kv_layout == "paged":
        for name, hlo in (("draft", ctx.spec_draft_hlo),
                          ("verify", ctx.spec_verify_hlo)):
            hits = host_transfer_ops(hlo) if hlo else []
            if hits:
                kinds = sorted({h["kind"] for h in hits})
                findings.append(Finding(
                    "speculative", SEV_ERROR,
                    f"paged speculative {name} program lowers "
                    f"{len(hits)} host transfer op(s) "
                    f"({', '.join(kinds)}) — page-table gathers must "
                    f"stay on device in every steady-state program",
                    {"program": name, "count": len(hits),
                     "kinds": kinds,
                     "ops": [h["line"][:200] for h in hits[:8]]}))
    if ctx.decode_attention_impl == "flash" and ctx.spec_draft_hlo:
        if ctx.decode_platform == "tpu" and \
                "custom-call" not in ctx.spec_draft_hlo:
            findings.append(Finding(
                "speculative", SEV_ERROR,
                "attention_impl='flash' on TPU but the draft program "
                "contains no custom-call — the T=1 draft step lost the "
                "Pallas flash-decode kernel",
                {"platform": ctx.decode_platform}))
        payload = ctx.decode_cache_payload_shape
        if payload:
            from deepspeed_tpu.analysis.hlo import payload_shaped_dots
            dots = payload_shaped_dots(ctx.spec_draft_hlo, payload)
            if dots:
                findings.append(Finding(
                    "speculative", SEV_ERROR,
                    f"attention_impl='flash' but the draft program "
                    f"still contracts over the full cache payload "
                    f"shape {tuple(payload)} in {len(dots)} dot(s) — "
                    f"dense attention survived in the draft step",
                    {"payload_shape": tuple(payload),
                     "dots": dots[:8]}))
    return findings


def rule_kernel_vmem(ctx):
    """Every pallas_call's per-grid-step working set fits in VMEM.

    The working set is the double-buffered input+output block bytes
    plus declared scratch (`kernels.KernelFacts.vmem_bytes`) against
    the platform budget (`cost.Platform.vmem_bytes`). Interpret-mode CI
    executes any block shape happily; on hardware an over-budget config
    is a Mosaic compile failure — this rule is the only place the
    constraint is checked before a TPU sees the program.
    """
    ana = ctx.kernel_analysis
    if ana is None:
        return []
    findings = []
    budget = ana.vmem_budget_bytes
    for k in ana.kernels:
        if k.vmem_bytes > budget:
            findings.append(Finding(
                "kernel_vmem", SEV_ERROR,
                f"kernel '{k.name}': per-grid-step VMEM working set "
                f"{_fmt_bytes(k.vmem_bytes)} exceeds the "
                f"{ana.platform} budget {_fmt_bytes(budget)} "
                f"(blocks {_fmt_bytes(k.block_bytes_per_step)} "
                f"double-buffered + scratch "
                f"{_fmt_bytes(k.scratch_bytes)})",
                {"kernel": k.name, "vmem_bytes": k.vmem_bytes,
                 "budget_bytes": budget,
                 "block_bytes_per_step": k.block_bytes_per_step,
                 "scratch_bytes": k.scratch_bytes,
                 "grid": list(k.grid)}))
    return findings


def rule_kernel_tiling(ctx):
    """Block trailing dims respect the dtype's native TPU tile.

    Native register tiles are (8, 128) f32, (16, 128) bf16, (32, 128)
    int8/fp8 (`kernels.SUBLANES`). A block whose lane dim is not a
    multiple of 128, or whose sublane dim is not a multiple of the
    dtype's sublane count, pads to full tiles on every load — silently
    wasting VMEM and bandwidth. Geometry-forced dims (block == array
    extent, singleton indexed dims) are exempt; see
    `kernels._tiling_lint`.
    """
    ana = ctx.kernel_analysis
    if ana is None:
        return []
    findings = []
    for k in ana.kernels:
        for t in k.tiling:
            findings.append(Finding(
                "kernel_tiling", SEV_WARNING,
                f"kernel '{k.name}' operand {t['operand']}: "
                f"{t['axis']} block dim {t['block_dim']} is not a "
                f"multiple of the {t['dtype']} native tile "
                f"{t['tile']} (array dim {t['array_dim']}) — every "
                f"touch pads to full tiles",
                {"kernel": k.name, **t}))
    return findings


def rule_kernel_dma(ctx):
    """Grid-write safety and the DMA-elision proof.

    An output block revisited at NON-consecutive grid steps is a race
    under Pallas's grid semantics: the block is flushed when the grid
    moves away, so the revisit reads back stale data (consecutive
    revisits are the legitimate carried-accumulator idiom and pass).

    When the audit declares an elision contract
    (``kernel_expected_elision``, the dead-block fraction implied by
    the analysis scenario's positions), the byte-weighted INPUT elided
    fraction proved by the index-map sweep must reach it — this is the
    static proof that the flash-decode clamp trick
    (`ops/pallas/flash_decode.py` ``kv_map``/``_physical``) actually
    turns dead cache blocks into elided DMAs, instead of asserting it
    in prose.
    """
    ana = ctx.kernel_analysis
    if ana is None:
        return []
    findings = []
    for k in ana.kernels:
        for race in k.races:
            findings.append(Finding(
                "kernel_dma", SEV_ERROR,
                f"kernel '{k.name}' operand {race['operand']}: output "
                f"block {tuple(race['block'])} is written at "
                f"non-consecutive grid steps {race['steps'][:6]} — "
                f"the block is flushed between visits and the revisit "
                f"reads stale data",
                {"kernel": k.name, **race}))
    if ctx.kernel_expected_elision is not None:
        in_dma = in_dense = 0
        unevaluated = []
        for k in ana.kernels:
            for op in k.operands:
                if op.kind != "input":
                    continue
                in_dma += op.dma_fetches * op.block_bytes
                in_dense += op.total_fetches * op.block_bytes
                if not op.index_map_evaluated:
                    unevaluated.append(f"{k.name}/{op.name}")
        proved = 1.0 - in_dma / in_dense if in_dense else 0.0
        expected = float(ctx.kernel_expected_elision)
        if unevaluated:
            findings.append(Finding(
                "kernel_dma", SEV_WARNING,
                f"elision contract declared but "
                f"{len(unevaluated)} operand index map(s) could not "
                f"be evaluated ({', '.join(unevaluated[:4])}) — the "
                f"DMA-elision proof is incomplete",
                {"unevaluated": unevaluated}))
        elif proved + 1e-6 < expected:
            findings.append(Finding(
                "kernel_dma", SEV_WARNING,
                f"index maps elide only {proved:.1%} of input block "
                f"DMAs; the scenario's occupancy requires "
                f"{expected:.1%} — dead cache blocks are being "
                f"fetched (unclamped index map?)",
                {"proved_elision": round(proved, 6),
                 "expected_elision": round(expected, 6),
                 "input_dma_bytes": in_dma,
                 "input_dense_bytes": in_dense}))
    return findings


# Rule catalog: id -> rule. `recompile` is listed for config validation
# but runs in the orchestrator (it needs live step objects, not HLO).
RULES = {
    "donation": rule_donation,
    "dtype_hygiene": rule_dtype_hygiene,
    "zero_budget": rule_zero_budget,
    "host_transfer": rule_host_transfer,
    "trip_count": rule_trip_count,
    "overlap": rule_overlap,
    "deadlock": rule_deadlock,
    "resharding": rule_resharding,
    "peak_memory": rule_peak_memory,
    "fp8": rule_fp8,
    "decode": rule_decode,
    "flash_decode": rule_flash_decode,
    "speculative": rule_speculative,
    "kernel_vmem": rule_kernel_vmem,
    "kernel_tiling": rule_kernel_tiling,
    "kernel_dma": rule_kernel_dma,
}
RULE_IDS = tuple(RULES) + ("recompile",)


def run_rules(ctx, rules=None):
    """Run the catalog (or the named subset) over one step's context."""
    findings = []
    for rule_id, rule in RULES.items():
        if rules is not None and rule_id not in rules:
            continue
        if rule_id in ctx.skip_rules:
            continue
        findings.extend(rule(ctx))
    findings.sort(key=lambda f: -_SEV_RANK.get(f.severity, 0))
    return findings
