"""Jaxpr-level static analysis: collective safety before XLA ever runs.

The HLO side of the audit (`analysis/hlo.py`) reads facts off the
*compiled* program; this module reads the **traced** program — the
closed jaxpr of a train step — where control flow (``cond``/``while``/
``scan``), mesh-axis data dependence, and collective ordering are still
first-class structure instead of partitioned channel ids. Three passes,
all pure functions over a :class:`jax.core.ClosedJaxpr`:

- :func:`check_divergent_collectives` — the PR 5 pipeline deadlock as a
  rule. Values derived from ``lax.axis_index`` are *device-varying*
  (tainted) over that mesh axis; a ``lax.cond`` whose predicate carries
  taint executes its branches divergently across devices. A
  ``ppermute`` inside such a branch deadlocks the in-process runtime
  outright (collective-permute rendezvous is GLOBAL — every device must
  arrive at the *same* op) and is invalid SPMD everywhere; a grouped
  collective (``psum``/``all_gather``/…) is flagged only when its own
  axis is among the divergent ones (devices of one rendezvous group
  taking different branches), which is why the seed's stage-divergent
  ``lax.cond`` survived while its collectives were per-``data``-group
  all-reduces and died the moment TP reductions chunked into permute
  rings. A ``while`` whose *trip count* is device-varying divergently
  executes everything inside it, so any collective in its body is
  flagged.
- :func:`check_unordered_permutes` — the ``barrier_after`` invariant,
  checked instead of assumed: every pair of ``ppermute``s that can be
  in flight concurrently must be ordered by a dataflow edge (the
  overlap library chains each emitted permute through
  ``parallel.collectives.barrier_after``). Two *independent* in-flight
  permutes split the in-process runtime's global rendezvous — half the
  devices arrive at one op, half at the other — and deadlock.
- :func:`propagate_partition_specs` — a lightweight sharding-flow
  interpreter: seed the jaxpr inputs with their PartitionSpecs and push
  them through shape-preserving ops, ``transpose``/``broadcast``/
  ``dot_general``, and control flow. Operands meeting with
  *conflicting* placements on the same dimension force a compiler-
  inserted reshard (all-gather + reslice) that no declared site
  accounts for — recorded as events the ``resharding`` rule sizes and
  reports.

Everything here runs at trace time: no compile, no execution — which is
the point, since the programs being checked for deadlocks must never be
run to find out.
"""

import dataclasses

import numpy as np

from jax import core as jcore

# Collective primitives by rendezvous discipline (jaxpr names).
# ``ppermute`` lowers to ``collective-permute`` whose rendezvous is
# global across the mesh — every device must reach the same op.
GLOBAL_RENDEZVOUS = ("ppermute",)
# Grouped collectives rendezvous per replica group along their own axes:
# divergence only breaks them when it splits a group.
GROUPED_COLLECTIVES = ("psum", "pmax", "pmin", "pmean", "all_gather",
                       "all_to_all", "reduce_scatter", "psum_scatter",
                       "pbroadcast", "pgather")
COLLECTIVE_PRIMITIVES = GLOBAL_RENDEZVOUS + GROUPED_COLLECTIVES

# Grouped collectives whose output is *uniform* along their axes (a
# reduction or gather makes every member hold the same value) — they
# erase device-variance taint. ``all_to_all``/``ppermute`` redistribute
# instead and keep (or introduce) variance.
_TAINT_ERASING = ("psum", "pmax", "pmin", "pmean", "all_gather",
                  "pbroadcast")


def _collective_axes(eqn):
    """Mesh axes a collective eqn rendezvouses over, as a tuple."""
    axes = eqn.params.get("axes",
                          eqn.params.get("axis_name",
                                         eqn.params.get("axis_index_groups")
                                         and ()))
    if axes is None:
        axes = ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if a is not None)


def _aval_bytes(aval):
    shape = tuple(getattr(aval, "shape", ()) or ())
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    itemsize = np.dtype(dtype).itemsize
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return n * itemsize


def _as_jaxprs(value):
    if isinstance(value, jcore.ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, jcore.Jaxpr):
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            if isinstance(v, jcore.ClosedJaxpr):
                yield v.jaxpr
            elif isinstance(v, jcore.Jaxpr):
                yield v


def subjaxpr_bindings(eqn):
    """``(jaxpr, binders, label)`` per sub-jaxpr of ``eqn``.

    ``binders`` aligns the inner jaxpr's invars with the eqn's invars
    (outer atoms, or None where no outer atom corresponds — e.g. branch
    binders past the operand list). Control-flow primitives get exact
    maps; anything else maps positionally when the arity matches and
    conservatively (all-None) when it doesn't.
    """
    p = eqn.primitive.name
    if p == "cond":
        ops = list(eqn.invars[1:])
        for i, br in enumerate(eqn.params["branches"]):
            yield br.jaxpr, ops, f"cond branch {i}"
        return
    if p == "while":
        cn = eqn.params["cond_nconsts"]
        bn = eqn.params["body_nconsts"]
        carry = list(eqn.invars[cn + bn:])
        yield (eqn.params["cond_jaxpr"].jaxpr,
               list(eqn.invars[:cn]) + carry, "while cond")
        yield (eqn.params["body_jaxpr"].jaxpr,
               list(eqn.invars[cn:cn + bn]) + carry, "while body")
        return
    if p == "scan":
        # invars = consts + carry + xs; the body binds them positionally
        # (xs as per-iteration slices — same taint/ordering semantics).
        yield eqn.params["jaxpr"].jaxpr, list(eqn.invars), "scan body"
        return
    for key, value in sorted(eqn.params.items()):
        for jx in _as_jaxprs(value):
            if len(jx.invars) == len(eqn.invars):
                binders = list(eqn.invars)
            else:
                binders = [None] * len(jx.invars)
            yield jx, binders, p


def _scan_length(eqn):
    if eqn.primitive.name == "scan":
        return int(eqn.params.get("length", 1))
    return 1


# ---------------------------------------------------------------------------
# collective site collection
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CollectiveSite:
    """One collective eqn in the traced program."""
    primitive: str
    axes: tuple
    path: tuple          # context stack, e.g. ("shard_map", "scan body")
    out_bytes: int       # device-local payload of one execution
    multiplier: int      # static execution count (product of scan trips)


def collect_collectives(closed_jaxpr):
    """Every collective eqn with its context path and static execution
    multiplier (``scan`` lengths compound; ``while`` counts as 1 — its
    trip count is the HLO side's problem)."""
    sites = []

    def walk(jaxpr, path, mult):
        for eqn in jaxpr.eqns:
            p = eqn.primitive.name
            if p in COLLECTIVE_PRIMITIVES:
                sites.append(CollectiveSite(
                    primitive=p,
                    axes=_collective_axes(eqn),
                    path=path,
                    out_bytes=sum(_aval_bytes(v.aval)
                                  for v in eqn.outvars),
                    multiplier=mult))
            sub_mult = mult * _scan_length(eqn)
            for jx, _, label in subjaxpr_bindings(eqn):
                walk(jx, path + (label,), sub_mult)

    walk(closed_jaxpr.jaxpr, (), 1)
    return sites


# ---------------------------------------------------------------------------
# pass 1: divergent collectives (the PR 5 stage-divergent cond, as a rule)
# ---------------------------------------------------------------------------

def check_divergent_collectives(closed_jaxpr):
    """Deadlock findings for collectives under device-varying control
    flow. Returns ``[{kind, message, path, primitive, axes,
    divergent_axes}]``; empty means the program is collective-uniform.
    """
    findings = []

    def warn(kind, eqn, path, div_axes, msg):
        findings.append({
            "kind": kind,
            "primitive": eqn.primitive.name,
            "axes": tuple(_collective_axes(eqn)),
            "divergent_axes": tuple(sorted(div_axes)),
            "path": path,
            "message": msg,
        })

    def walk(jaxpr, in_taints, path, div_axes, loop_div):
        """Returns per-outvar taints. ``div_axes``: axes the current
        control-flow context diverges over; ``loop_div``: inside a while
        whose trip count is device-varying."""
        env = {}

        def read(atom):
            if isinstance(atom, jcore.Literal):
                return frozenset()
            return env.get(atom, frozenset())

        def write(var, taint):
            if not isinstance(var, jcore.DropVar):
                env[var] = taint

        for var, t in zip(jaxpr.invars, in_taints):
            write(var, t)
        for var in jaxpr.constvars:
            write(var, frozenset())

        for eqn in jaxpr.eqns:
            p = eqn.primitive.name
            in_t = [read(a) for a in eqn.invars]
            joined = frozenset().union(*in_t) if in_t else frozenset()

            if p in COLLECTIVE_PRIMITIVES:
                axes = _collective_axes(eqn)
                if loop_div:
                    warn("deadlock", eqn, path, div_axes,
                         f"{p} over {axes} inside a while loop whose "
                         f"trip count varies across devices of mesh "
                         f"axis(es) {tuple(sorted(div_axes))} — devices "
                         f"exit the loop at different iterations and "
                         f"miss the rendezvous")
                elif div_axes and p in GLOBAL_RENDEZVOUS:
                    warn("deadlock", eqn, path, div_axes,
                         f"{p} over {axes} executes inside control flow "
                         f"divergent over mesh axis(es) "
                         f"{tuple(sorted(div_axes))} — collective-"
                         f"permute rendezvous is global, so devices "
                         f"taking the other branch never arrive (the "
                         f"PR 5 stage-divergent pipeline deadlock)")
                elif div_axes and set(axes) & div_axes:
                    hit = tuple(sorted(set(axes) & div_axes))
                    warn("deadlock", eqn, path, div_axes,
                         f"{p} over {axes} executes inside control flow "
                         f"divergent over its own axis(es) {hit} — "
                         f"members of one rendezvous group take "
                         f"different branches")

            if p == "axis_index":
                ax = eqn.params.get("axis_name")
                ax = ax if isinstance(ax, (tuple, list)) else (ax,)
                out_taint = joined | frozenset(a for a in ax
                                               if a is not None)
            elif p in _TAINT_ERASING:
                out_taint = joined - frozenset(_collective_axes(eqn))
            elif p == "all_to_all":
                out_taint = joined | frozenset(_collective_axes(eqn))
            else:
                out_taint = joined

            if p == "cond":
                pred_t = read(eqn.invars[0])
                sub_div = div_axes | pred_t
                out_ts = None
                for jx, binders, label in subjaxpr_bindings(eqn):
                    bt = [read(b) if b is not None else frozenset()
                          for b in binders]
                    branch_out = walk(jx, bt, path + (label,),
                                      sub_div if pred_t else div_axes,
                                      loop_div)
                    if out_ts is None:
                        out_ts = list(branch_out)
                    else:
                        out_ts = [a | b for a, b in zip(out_ts,
                                                        branch_out)]
                for var, t in zip(eqn.outvars, out_ts or []):
                    write(var, t | pred_t)
                continue

            if p == "while":
                cn = eqn.params["cond_nconsts"]
                bn = eqn.params["body_nconsts"]
                cond_jx = eqn.params["cond_jaxpr"].jaxpr
                body_jx = eqn.params["body_jaxpr"].jaxpr
                cconst_t = in_t[:cn]
                bconst_t = in_t[cn:cn + bn]
                carry_t = list(in_t[cn + bn:])
                # Taint-fixpoint over the carry (taints only grow).
                for _ in range(len(carry_t) + 2):
                    body_out = walk(body_jx, bconst_t + carry_t,
                                    path + ("while body",), div_axes,
                                    loop_div)
                    new_carry = [a | b for a, b in zip(carry_t,
                                                       body_out)]
                    if new_carry == carry_t:
                        break
                    carry_t = new_carry
                (cond_t,) = walk(cond_jx, cconst_t + carry_t,
                                 path + ("while cond",), div_axes,
                                 loop_div)
                if cond_t:
                    # Device-varying trip count: re-walk the body in
                    # loop-divergent mode so every collective inside is
                    # flagged.
                    walk(body_jx, bconst_t + carry_t,
                         path + ("while body",), div_axes | cond_t,
                         True)
                for var, t in zip(eqn.outvars, carry_t):
                    write(var, t | cond_t)
                continue

            if p == "scan":
                nc = eqn.params["num_consts"]
                ncar = eqn.params["num_carry"]
                jx = eqn.params["jaxpr"].jaxpr
                const_t = in_t[:nc]
                carry_t = list(in_t[nc:nc + ncar])
                xs_t = in_t[nc + ncar:]
                for _ in range(len(carry_t) + 2):
                    body_out = walk(jx, const_t + carry_t + xs_t,
                                    path + ("scan body",), div_axes,
                                    loop_div)
                    new_carry = [a | b for a, b in
                                 zip(carry_t, body_out[:ncar])]
                    if new_carry == carry_t:
                        break
                    carry_t = new_carry
                out_ts = carry_t + list(body_out[ncar:])
                for var, t in zip(eqn.outvars, out_ts):
                    write(var, t)
                continue

            handled_sub = False
            for jx, binders, label in subjaxpr_bindings(eqn):
                handled_sub = True
                bt = [read(b) if b is not None else joined
                      for b in binders]
                sub_out = walk(jx, bt, path + (label,), div_axes,
                               loop_div)
                if len(sub_out) == len(eqn.outvars):
                    for var, t in zip(eqn.outvars, sub_out):
                        write(var, t)
                else:
                    sub_joined = (frozenset().union(*sub_out)
                                  if sub_out else frozenset())
                    for var in eqn.outvars:
                        write(var, joined | sub_joined)
            if handled_sub:
                continue

            for var in eqn.outvars:
                write(var, out_taint)

        return [read(v) for v in jaxpr.outvars]

    jaxpr = closed_jaxpr.jaxpr
    walk(jaxpr, [frozenset()] * len(jaxpr.invars), (), frozenset(), False)
    return findings


# ---------------------------------------------------------------------------
# pass 2: unordered concurrent collective-permutes (barrier_after, checked)
# ---------------------------------------------------------------------------

def check_unordered_permutes(closed_jaxpr, max_findings=16):
    """Pairs of ``ppermute``s with no dataflow ordering between them.

    Within each (sub-)jaxpr body, every eqn that (transitively) emits a
    ``ppermute`` must be an ancestor or descendant of every other —
    i.e. the emitted permutes form one dependency chain, the invariant
    ``parallel.collectives.barrier_after`` exists to maintain. Branch
    bodies of one ``cond`` are checked independently (they never
    co-execute). Returns ``[{kind, message, path, eqns}]``.
    """
    findings = []
    emits_cache = {}

    def emits_permute(jaxpr):
        key = id(jaxpr)
        if key not in emits_cache:
            emits_cache[key] = False  # cycle-safe default
            found = False
            for eqn in jaxpr.eqns:
                if eqn.primitive.name in GLOBAL_RENDEZVOUS:
                    found = True
                    break
                for jx, _, _ in subjaxpr_bindings(eqn):
                    if emits_permute(jx):
                        found = True
                        break
                if found:
                    break
            emits_cache[key] = found
        return emits_cache[key]

    def walk(jaxpr, path):
        producer = {}
        anc = []
        permute_eqns = []   # [(idx, label)]
        for i, eqn in enumerate(jaxpr.eqns):
            mask = 0
            for a in eqn.invars:
                j = producer.get(a) if not isinstance(a, jcore.Literal) \
                    else None
                if j is not None:
                    mask |= anc[j] | (1 << j)
            anc.append(mask)
            emits = eqn.primitive.name in GLOBAL_RENDEZVOUS
            for jx, _, label in subjaxpr_bindings(eqn):
                walk(jx, path + (label,))
                emits = emits or emits_permute(jx)
            if emits:
                for j, j_label in permute_eqns:
                    if not (mask >> j) & 1 and len(findings) < \
                            max_findings:
                        findings.append({
                            "kind": "unordered_permutes",
                            "path": path,
                            "eqns": (j_label,
                                     str(eqn.primitive.name)),
                            "message":
                                f"two collective-permute-emitting ops "
                                f"({j_label!s} and "
                                f"{eqn.primitive.name}) share no "
                                f"dataflow edge at {'/'.join(path) or 'top level'} — both can be "
                                f"in flight at once, splitting the "
                                f"global rendezvous (chain them with "
                                f"parallel.collectives.barrier_after)",
                        })
                permute_eqns.append((i, eqn.primitive.name))
            for v in eqn.outvars:
                if not isinstance(v, jcore.DropVar):
                    producer[v] = i

    walk(closed_jaxpr.jaxpr, ())
    return findings


# ---------------------------------------------------------------------------
# pass 3: PartitionSpec flow (sharding lint)
# ---------------------------------------------------------------------------

UNKNOWN = object()     # spec lattice top: propagation lost track


def _norm_entry(e):
    if e is None:
        return None
    if isinstance(e, (tuple, list)):
        return tuple(e)
    return (e,)


def spec_tuple(spec, rank):
    """A PartitionSpec (or tuple) normalized to exactly ``rank`` per-dim
    entries (None = replicated; tuple of axis names = sharded)."""
    entries = [_norm_entry(e) for e in tuple(spec or ())]
    entries = entries[:rank]
    entries += [None] * (rank - len(entries))
    return tuple(entries)


def _join_specs(specs, avals):
    """Join same-shaped operand specs; returns (spec | UNKNOWN, conflict
    dim | None). Replicated joins with anything (a further slice, no
    comm). A conflict — a reshard the compiler must insert — is either
    two different non-None placements on one dim, or the same mesh axis
    claimed by different dims of different operands."""
    known = [(s, a) for s, a in zip(specs, avals)
             if s is not UNKNOWN and getattr(a, "shape", None) is not None]
    if not known:
        return UNKNOWN, None
    rank = max(len(s) for s, _ in known)
    out = [None] * rank
    axis_dim = {}        # mesh axis name -> dim it shards in the join
    conflict = None
    for s, _ in known:
        for d, e in enumerate(s):
            if e is None:
                continue
            if out[d] is None:
                out[d] = e
            elif out[d] != e:
                conflict = d
            for axis in e:
                if axis_dim.setdefault(axis, d) != d:
                    conflict = d
    return tuple(out), conflict


@dataclasses.dataclass
class ReshardEvent:
    """A point where propagation saw placements forcibly change."""
    kind: str            # "conflict"
    primitive: str
    path: tuple
    dim: int
    bytes: int           # size of the largest operand involved
    specs: tuple         # the operand spec tuples that collided


def propagate_partition_specs(closed_jaxpr, in_specs):
    """Push per-dim PartitionSpec entries through the jaxpr.

    ``in_specs``: one PartitionSpec (or per-dim tuple, or None for
    replicated) per jaxpr invar. Returns ``(out_specs, events)`` where
    ``out_specs`` has an entry (tuple | UNKNOWN) per outvar and
    ``events`` lists :class:`ReshardEvent`s — operands meeting with
    conflicting placements, i.e. compiler-inserted reshards no declared
    site accounts for.

    Deliberately partial: shape-preserving ops, ``transpose``,
    ``broadcast_in_dim``, ``squeeze``/``expand_dims``, ``dot_general``,
    ``convert_element_type`` and control flow propagate; anything else
    (including everything inside ``shard_map``, whose body is manual)
    degrades to UNKNOWN instead of guessing.
    """
    events = []

    def walk(jaxpr, specs_in, path):
        env = {}

        def read(atom):
            if isinstance(atom, jcore.Literal):
                return spec_tuple(None, np.ndim(atom.val))
            return env.get(atom, UNKNOWN)

        def write(var, spec):
            if not isinstance(var, jcore.DropVar):
                env[var] = spec

        for var, s in zip(jaxpr.invars, specs_in):
            rank = len(getattr(var.aval, "shape", ()) or ())
            write(var, UNKNOWN if s is UNKNOWN
                  else spec_tuple(s, rank))
        for var in jaxpr.constvars:
            rank = len(getattr(var.aval, "shape", ()) or ())
            write(var, spec_tuple(None, rank))

        for eqn in jaxpr.eqns:
            p = eqn.primitive.name
            in_s = [read(a) for a in eqn.invars]
            avals = [getattr(a, "aval", None) for a in eqn.invars]
            out_rank = [len(getattr(v.aval, "shape", ()) or ())
                        for v in eqn.outvars]

            if p == "sharding_constraint":
                sh = eqn.params.get("sharding")
                spec = getattr(sh, "spec", None)
                write(eqn.outvars[0],
                      spec_tuple(spec, out_rank[0]) if spec is not None
                      else UNKNOWN)
                continue

            if p == "transpose":
                s = in_s[0]
                if s is UNKNOWN:
                    write(eqn.outvars[0], UNKNOWN)
                else:
                    perm = eqn.params["permutation"]
                    write(eqn.outvars[0], tuple(s[d] for d in perm))
                continue

            if p == "broadcast_in_dim":
                s = in_s[0]
                out = [None] * out_rank[0]
                if s is not UNKNOWN:
                    for src, dst in enumerate(
                            eqn.params["broadcast_dimensions"]):
                        out[dst] = s[src]
                    write(eqn.outvars[0], tuple(out))
                else:
                    write(eqn.outvars[0], UNKNOWN)
                continue

            if p in ("squeeze", "expand_dims"):
                s = in_s[0]
                if s is UNKNOWN:
                    write(eqn.outvars[0], UNKNOWN)
                    continue
                in_shape = tuple(avals[0].shape)
                if p == "squeeze":
                    dims = set(eqn.params["dimensions"])
                    write(eqn.outvars[0],
                          tuple(e for d, e in enumerate(s)
                                if d not in dims))
                else:
                    out = list(s)
                    for d in sorted(eqn.params["dimensions"]):
                        out.insert(d, None)
                    write(eqn.outvars[0], tuple(out))
                del in_shape
                continue

            if p == "dot_general":
                ls, rs = in_s[0], in_s[1]
                if ls is UNKNOWN or rs is UNKNOWN:
                    write(eqn.outvars[0], UNKNOWN)
                    continue
                ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
                lfree = [d for d in range(len(ls))
                         if d not in lc and d not in lb]
                rfree = [d for d in range(len(rs))
                         if d not in rc and d not in rb]
                out = tuple([ls[d] for d in lb]
                            + [ls[d] for d in lfree]
                            + [rs[d] for d in rfree])
                write(eqn.outvars[0], out)
                continue

            if p == "cond":
                out_specs = None
                for jx, binders, label in subjaxpr_bindings(eqn):
                    bs = [read(b) if b is not None else UNKNOWN
                          for b in binders]
                    branch_out = walk(jx, bs, path + (label,))
                    if out_specs is None:
                        out_specs = list(branch_out)
                    else:
                        out_specs = [
                            a if (a is not UNKNOWN and a == b) else
                            UNKNOWN
                            for a, b in zip(out_specs, branch_out)]
                for var, s in zip(eqn.outvars, out_specs or []):
                    write(var, s)
                continue

            if p == "scan":
                nc = eqn.params["num_consts"]
                ncar = eqn.params["num_carry"]
                jx = eqn.params["jaxpr"].jaxpr
                # xs lose their leading (scanned) dim inside the body.
                xs_specs = [UNKNOWN if s is UNKNOWN else tuple(s[1:])
                            for s in in_s[nc + ncar:]]
                body_in = in_s[:nc + ncar] + xs_specs
                body_out = walk(jx, body_in, path + ("scan body",))
                carry_out = [
                    a if (a is not UNKNOWN and a == b) else UNKNOWN
                    for a, b in zip(body_out[:ncar],
                                    in_s[nc:nc + ncar])]
                ys = [UNKNOWN if s is UNKNOWN else (None,) + tuple(s)
                      for s in body_out[ncar:]]
                for var, s in zip(eqn.outvars, carry_out + ys):
                    write(var, s)
                continue

            if p == "pjit":
                for jx, binders, label in subjaxpr_bindings(eqn):
                    sub_out = walk(jx, in_s, path + (label,))
                    if len(sub_out) == len(eqn.outvars):
                        for var, s in zip(eqn.outvars, sub_out):
                            write(var, s)
                    else:
                        for var in eqn.outvars:
                            write(var, UNKNOWN)
                continue

            has_sub = False
            for jx, _, label in subjaxpr_bindings(eqn):
                has_sub = True
                # Opaque call (shard_map bodies are manual; custom_vjp
                # wraps its own trace): still recurse so nested passes
                # COULD see it, but specs inside are not meaningful —
                # degrade outputs to UNKNOWN.
                walk(jx, [UNKNOWN] * len(jx.invars), path + (label,))
            if has_sub:
                for var in eqn.outvars:
                    write(var, UNKNOWN)
                continue

            # Structural elementwise rule: all non-scalar operands share
            # the output shape → join their specs (conflicts = forced
            # reshard), scalars ride along.
            if len(eqn.outvars) == 1 and out_rank[0] > 0:
                peers = [(s, a) for s, a in zip(in_s, avals)
                         if a is not None
                         and tuple(getattr(a, "shape", ()) or ()) ==
                         tuple(eqn.outvars[0].aval.shape)]
                if peers and all(s is not UNKNOWN for s, _ in peers):
                    joined, conflict = _join_specs(
                        [s for s, _ in peers], [a for _, a in peers])
                    if conflict is not None:
                        events.append(ReshardEvent(
                            kind="conflict", primitive=p, path=path,
                            dim=conflict,
                            bytes=max(_aval_bytes(a) for _, a in peers),
                            specs=tuple(s for s, _ in peers)))
                        joined = UNKNOWN
                    write(eqn.outvars[0], joined)
                    continue
            for var in eqn.outvars:
                write(var, UNKNOWN)

        return [read(v) for v in jaxpr.outvars]

    jaxpr = closed_jaxpr.jaxpr
    n = len(jaxpr.invars)
    seeds = list(in_specs) + [UNKNOWN] * (n - len(in_specs))
    out = walk(jaxpr, seeds[:n], ())
    return out, events


# ---------------------------------------------------------------------------
# tracing front door
# ---------------------------------------------------------------------------

def trace_jaxpr(fn, args, fresh=True):
    """ClosedJaxpr of a (jitted or plain) step function at ``args``'
    avals — a retrace, never a compile.

    ``fresh=True`` (default) traces the *unwrapped* callable
    (``fn.__wrapped__`` for a jitted fn) so the Python body actually
    re-runs: trace-time instrumentation — the
    ``parallel.collectives`` site log, the pipeline trace fixtures —
    only fires on a genuine retrace, and a jitted ``fn.trace`` is
    served from the jit cache after the step has compiled.
    ``fresh=False`` takes the cache-sharing path (cheapest when only
    the jaxpr itself is needed)."""
    import jax

    if fresh:
        # Unwrap the jit boundary, then trace through a THROWAWAY lambda:
        # the pjit trace cache is keyed on the underlying function object,
        # so make_jaxpr of the long-lived step fn is a cache hit that
        # skips its Python body entirely. A fresh closure per call forces
        # the body to actually re-run.
        inner = getattr(fn, "__wrapped__", None)
        target = inner if callable(inner) else fn
        return jax.make_jaxpr(lambda *a: target(*a))(*args)
    trace = getattr(fn, "trace", None)
    if callable(trace):
        return trace(*args).jaxpr
    return jax.make_jaxpr(fn)(*args)


def input_specs_of(args):
    """Per-flat-leaf PartitionSpecs of concrete call arguments: committed
    ``jax.Array``s report their NamedSharding spec; anything else
    (numpy, scalars) is treated as replicated."""
    import jax

    specs = []
    for leaf in jax.tree_util.tree_leaves(args):
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        rank = np.ndim(leaf)
        specs.append(spec_tuple(spec, rank))
    return specs
