"""HLO parsing core for the compiled-program audit subsystem.

Under XLA every collective, buffer alias, and dtype decision in a train
step is a *compile-time* artifact: an HLO op with a static shape, an
``input_output_alias`` entry in the module header, a ``while`` loop with
a known trip count. This module reads those facts off ``compile()``'s
``as_text()`` dump so the audit rules (`analysis/rules.py`) can check
them against what the engine *declared* it wanted.

Accounting is **trip-count-aware**: HLO programs are split into their
computations, the call graph (``while`` body/condition, ``calls=``,
``to_apply=``, conditional branches) is walked from ENTRY, and each
computation gets an execution multiplier — a collective inside a
``lax.scan``-lowered ``while`` with ``known_trip_count n=K`` counts K
times, not once. This fixes the historical flat-program limitation of
``utils/hlo_analysis.py`` (each op counted ONCE, so the executed-1F1B
pipeline's per-tick ``collective-permute`` volume was unpinnable); that
module is now a thin compatibility shim over this one. Text without any
computation headers (hand-written snippets in tests) falls back to flat
counting, and ``trip_aware=False`` restores the old behavior exactly.
"""

import re

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    # fp8 families (quantized-comm futures): 1 byte each.
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# e.g. "f32[8,128]{1,0}" or "u8[16]" or "f32[]" or "f8e4m3fn[256]"
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

# `%name = <shape-or-tuple> <op>(` — ops may be async "-start" forms;
# "-done" forms return the same buffer and are skipped to avoid double
# counting.
_COLLECTIVES = ("all-reduce", "all-gather", "all-to-all", "reduce-scatter",
                "collective-permute", "collective-broadcast")
# The shape is everything between "=" and the op name — matched
# non-greedily so nested variadic tuples like ((f32[8], f32[4]),
# (f32[8], f32[4])) capture whole (a "[^)]*" shape class truncates them
# at the first close-paren and silently undercounts).
_OP_RE = re.compile(
    r"=\s+(?P<shape>.+?)\s+"
    r"(?P<op>" + "|".join(_COLLECTIVES) + r")(?P<suffix>-start|-done)?\(")


def _element_bytes(shape_text, skip_scalars=False):
    """(dtype, bytes) of each array element appearing in a (tuple) shape.
    ``skip_scalars`` drops zero-rank elements (async-start context/scratch
    scalars like ``u32[]``, which are bookkeeping, not payload)."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _DTYPE_BYTES:
            continue  # token/opaque types carry no payload
        if skip_scalars and not dims:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        sizes.append((dtype, n * _DTYPE_BYTES[dtype]))
    return sizes


def _shape_bytes(shape_text):
    return sum(b for _, b in _element_bytes(shape_text))


# ---------------------------------------------------------------------------
# computation splitting and the execution-multiplier call-graph walk
# ---------------------------------------------------------------------------

# Computation headers sit at column 0 and look like
#   `%region_0.13_spmd (param.1: (s32[], f32[4])) -> (s32[], f32[4]) {`
# or `ENTRY %main.48_spmd (param.2: f32[6,4]) -> f32[4] {`
# while op lines are indented — the parse keys off that.
_HEADER_NAME_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)")

_COND_REF_RE = re.compile(r"condition=%?([\w.\-]+)")
_BODY_REF_RE = re.compile(r"body=%?([\w.\-]+)")
_CALLS_REF_RE = re.compile(r"calls=%?([\w.\-]+)")
_TO_APPLY_RE = re.compile(r"to_apply=%?([\w.\-]+)")
_TRUE_REF_RE = re.compile(r"true_computation=%?([\w.\-]+)")
_FALSE_REF_RE = re.compile(r"false_computation=%?([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
# `backend_config={"known_trip_count":{"n":"6"}}` on the while op line.
_TRIP_RE = re.compile(r"known_trip_count[^0-9]*(\d+)")
# Fallback: the scan-lowered condition is `i < constant(K)` with the
# induction variable starting at 0 and stepping by 1.
_COND_CONST_RE = re.compile(r"=\s+s(?:32|64)\[\]\s+constant\((\d+)\)")
_COND_LT_RE = re.compile(r"compare\(.*direction=LT")


def split_computations(hlo_text):
    """``(computations, entry_name)``: computation name -> body text.

    Returns ``({}, None)`` for text with no computation headers (e.g.
    hand-written op snippets), which callers treat as one flat program.
    """
    comps = {}
    entry = None
    buf = None
    for line in hlo_text.splitlines():
        if not line:
            continue
        if line.startswith("HloModule"):
            continue
        if line[0] not in " \t}" and "{" in line and "->" in line \
                and "(" in line:
            m = _HEADER_NAME_RE.match(line)
            if m:
                buf = []
                comps[m.group(2)] = buf
                if m.group(1):
                    entry = m.group(2)
            continue
        if line.startswith("}"):
            buf = None
            continue
        if buf is not None:
            buf.append(line)
    return comps, entry


def _while_trip_count(line, comps):
    """Static trip count of a ``while`` op line, or None if unknown."""
    m = _TRIP_RE.search(line)
    if m:
        return int(m.group(1))
    cond = _COND_REF_RE.search(line)
    if cond and cond.group(1) in comps:
        body = "\n".join(comps[cond.group(1)])
        consts = _COND_CONST_RE.findall(body)
        if len(consts) == 1 and _COND_LT_RE.search(body):
            return int(consts[0])
    return None


def _computation_edges(name, lines, comps):
    """Call-graph edges out of one computation:
    ``[(child, factor, is_while_body)]``."""
    edges = []
    for line in lines:
        if " while(" in line:
            trip = _while_trip_count(line, comps)
            bm = _BODY_REF_RE.search(line)
            cm = _COND_REF_RE.search(line)
            if bm:
                edges.append((bm.group(1), trip if trip else 1, trip))
            if cm:
                # the condition runs trip+1 times; collectives inside
                # conditions are pathological but account them anyway
                edges.append((cm.group(1), trip + 1 if trip else 1, None))
            continue
        for rx in (_CALLS_REF_RE, _TO_APPLY_RE, _TRUE_REF_RE,
                   _FALSE_REF_RE):
            m = rx.search(line)
            if m:
                edges.append((m.group(1), 1, None))
        m = _BRANCHES_RE.search(line)
        if m:
            for ref in m.group(1).split(","):
                ref = ref.strip().lstrip("%")
                if ref:
                    edges.append((ref, 1, None))
    return edges


def while_loops(hlo_text):
    """Every ``while`` op in the program: ``[{body, condition,
    trip_count, has_collectives, parent}]``. ``trip_count`` is None when
    neither the ``known_trip_count`` backend config nor the canonical
    `i < K` condition shape is present — volume through that loop cannot
    be statically accounted."""
    comps, _ = split_computations(hlo_text)
    loops = []
    for name, lines in comps.items():
        for line in lines:
            if " while(" not in line:
                continue
            bm = _BODY_REF_RE.search(line)
            cm = _COND_REF_RE.search(line)
            body = bm.group(1) if bm else None
            body_text = "\n".join(comps.get(body, []))
            loops.append({
                "parent": name,
                "body": body,
                "condition": cm.group(1) if cm else None,
                "trip_count": _while_trip_count(line, comps),
                "has_collectives": bool(_OP_RE.search(body_text)),
            })
    return loops


def computation_multipliers(hlo_text):
    """Execution count of every computation, walked from ENTRY.

    A ``while`` body's multiplier is its parent's times the static trip
    count (1 when the trip count is unknown — the old flat behavior,
    surfaced separately by ``while_loops`` so rules can flag it).
    Computations reachable through several call sites accumulate the sum
    of their path multipliers. Returns ``{}`` when the text has no
    parsable computations.
    """
    comps, entry = split_computations(hlo_text)
    if not comps or entry is None:
        return {}
    edges = {name: _computation_edges(name, lines, comps)
             for name, lines in comps.items()}
    mult = {name: 0 for name in comps}
    mult[entry] = 1

    def walk(name, m):
        for child, factor, _ in edges.get(name, ()):
            if child not in mult:
                continue
            mult[child] += m * factor
            walk(child, m * factor)

    walk(entry, 1)
    return mult


# ---------------------------------------------------------------------------
# collective accounting
# ---------------------------------------------------------------------------

def collective_ops(hlo_text, trip_aware=True):
    """Every collective op with its execution weight:
    ``[{op, computation, multiplier, dtype_bytes: {dtype: bytes}}]``.

    ``dtype_bytes`` is ONE execution's output payload; multiply by
    ``multiplier`` for per-step volume (``collective_bytes`` does).
    """
    if trip_aware:
        mult = computation_multipliers(hlo_text)
    else:
        mult = {}
    if mult:
        comps, _ = split_computations(hlo_text)
        segments = [(name, "\n".join(lines), mult.get(name, 0))
                    for name, lines in comps.items()]
    else:
        segments = [(None, hlo_text, 1)]
    ops = []
    for comp_name, text, m in segments:
        for match in _OP_RE.finditer(text):
            if match.group("suffix") == "-done":
                continue
            shape = match.group("shape")
            # async-start outputs are (operands..., results..., scratch...):
            # count only the result half. Halving the whole tuple's bytes
            # is exact only for symmetric collectives (all-reduce);
            # all-gather-start / reduce-scatter-start pair shard-sized
            # operands with differently-sized results. Scratch entries are
            # zero-rank scalars (collective-permute-start appends two
            # u32[] contexts) — drop them FIRST, then the remaining
            # flattened list is (operands..., results...) with matching
            # counts, variadic included, and the second half is the
            # results.
            if match.group("suffix") == "-start" and shape.startswith("("):
                elems = _element_bytes(shape, skip_scalars=True)
                elems = elems[len(elems) // 2:]
            else:
                elems = _element_bytes(shape)
            per = {}
            for dtype, b in elems:
                per[dtype] = per.get(dtype, 0) + b
            ops.append({"op": match.group("op"), "computation": comp_name,
                        "multiplier": m, "dtype_bytes": per})
    return ops


def collective_counts(hlo_text, trip_aware=True):
    """Execution counts of every collective op: ``{op_name: count}``.

    The per-step number of times each collective RUNS — ops inside a
    ``while``/``scan`` body count once per trip (``trip_aware=True``,
    the default). The byte-free companion of :func:`collective_bytes`,
    for pins on op *mix* (e.g. the overlap rule: a chunked collective
    matmul must show ``collective-permute`` executions where the
    monolithic form had ``all-reduce``)."""
    counts = {}
    for op in collective_ops(hlo_text, trip_aware=trip_aware):
        counts[op["op"]] = counts.get(op["op"], 0) + op["multiplier"]
    return counts


def collective_bytes(hlo_text, by_dtype=False, trip_aware=True):
    """Sum output bytes of every collective op in an HLO dump.

    Returns ``{op_name: bytes, ..., "total": bytes}``. Async pairs are
    counted once (the ``-start``, result element only — its output tuple
    also aliases the operand); sync tuple outputs sum their array
    elements. With ``trip_aware=True`` (the default) an op inside a
    ``while``/``scan`` body is weighted by the loop's static trip count —
    ``trip_aware=False`` restores the old one-count-per-op behavior.
    For ``all-reduce``/``all-to-all`` the output size equals the input
    size, so "output bytes" is the per-device payload in both directions
    of a symmetric exchange — a consistent basis for *ratios* between two
    programs, which is what the tests pin.

    With ``by_dtype=True`` every per-op entry is a ``{dtype: bytes}``
    dict instead ("total" stays a plain sum) — how the quantized-allreduce
    proof separates the int8 gradient exchange from same-op fp32 traffic
    (scale vectors, the ZeRO-1 param-refresh gather) sharing the program.
    """
    counts = {}
    for op in collective_ops(hlo_text, trip_aware=trip_aware):
        per_op = counts.setdefault(op["op"], {})
        for dtype, b in op["dtype_bytes"].items():
            per_op[dtype] = per_op.get(dtype, 0) + b * op["multiplier"]
    if by_dtype:
        out = {op: dict(d) for op, d in counts.items()}
        out["total"] = sum(b for d in counts.values() for b in d.values())
        return out
    flat = {op: sum(d.values()) for op, d in counts.items()}
    flat["total"] = sum(flat.values())
    return flat


# Result shapes of fp8 family, e.g. "%q = f8e4m3fn[64,256] convert(...)".
# Tuple results open with "(", so the optional paren is matched too.
_FP8_RESULT_RE = re.compile(r"=\s*\(?\s*(f8[a-z0-9]+)\[")


def fp8_value_counts(hlo_text, trip_aware=True):
    """Execution counts of ops producing fp8-typed values: ``{dtype: n}``.

    The fp8 qdq pair (`ops/fp8.py`) lowers each quantize to a
    ``convert`` whose RESULT shape is an fp8 dtype — on CPU the converts
    stay explicit next to the f32 dot, on TPU XLA fuses them into the
    native fp8 GEMM, but either way the lowered text carries the
    fp8-typed values. Forward operands show as ``f8e4m3fn``, backward
    cotangents as ``f8e5m2`` — the fp8 audit rule pins both. With
    ``trip_aware=True`` ops inside while/scan bodies count once per
    trip (same accounting as :func:`collective_counts`)."""
    mult = computation_multipliers(hlo_text) if trip_aware else {}
    if mult:
        comps, _ = split_computations(hlo_text)
        segments = [("\n".join(lines), mult.get(name, 0))
                    for name, lines in comps.items()]
    else:
        segments = [(hlo_text, 1)]
    out = {}
    for text, m in segments:
        for hit in _FP8_RESULT_RE.finditer(text):
            dt = hit.group(1)
            out[dt] = out.get(dt, 0) + m
    return out


# Per-device ring-algorithm send bytes as a multiple of the op's OUTPUT
# bytes (N = ring size): all-reduce sends 2·(N-1)/N · M; all-gather sends
# (N-1)/N · M (output M, shard M/N moved N-1 times); reduce-scatter
# output is the M/N shard but each device sends M·(N-1)/N = (N-1)·out;
# all-to-all and collective-permute move (N-1)/N and 1× their payload.
_RING_SEND_FACTORS = {
    "all-reduce": lambda n: 2 * (n - 1) / n,
    "all-gather": lambda n: (n - 1) / n,
    "reduce-scatter": lambda n: float(n - 1),
    "all-to-all": lambda n: (n - 1) / n,
    "collective-permute": lambda n: 1.0,
    "collective-broadcast": lambda n: 1.0,
}
# Every parsed collective must have a send factor — fail at import, not
# at some caller's KeyError, when _COLLECTIVES grows.
assert set(_RING_SEND_FACTORS) == set(_COLLECTIVES)


def ring_send_bytes(hlo_text, n_devices, by_dtype=False, trip_aware=True):
    """Per-device bytes each device *sends* under ring algorithms.

    Converts ``collective_bytes``'s output-bytes basis into the send-volume
    basis the ZeRO paper's communication claims use (2M for an all-reduce
    of M bytes, M for all-gather / reduce-scatter) so ratios between
    compiled programs can be compared against published numbers directly.
    Approximation: every collective is assumed to span ``n_devices`` (true
    for the single-axis ZeRO tests this backs; subgroup collectives would
    need per-op replica-group parsing).

    ``by_dtype=True`` keys each op's sends by element dtype, mirroring
    ``collective_bytes(by_dtype=True)``; ``trip_aware`` as there.
    """
    out = collective_bytes(hlo_text, by_dtype=True, trip_aware=trip_aware)
    sends = {}
    for op, d in out.items():
        if op == "total":
            continue
        factor = _RING_SEND_FACTORS[op](n_devices)
        sends[op] = {dt: int(b * factor) for dt, b in d.items()}
    if by_dtype:
        sends["total"] = sum(b for d in sends.values() for b in d.values())
        return sends
    flat = {op: sum(d.values()) for op, d in sends.items()}
    flat["total"] = sum(flat.values())
    return flat


# ---------------------------------------------------------------------------
# static peak-memory estimation (buffer liveness over the schedule)
# ---------------------------------------------------------------------------

# Ops that define views or bookkeeping, not fresh device buffers.
_ZERO_COST_OPS = {"parameter", "get-tuple-element", "tuple", "bitcast",
                  "after-all", "add-dependency", "partition-id",
                  "replica-id", "opt-barrier"}

_PEAK_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*(?P<shape>.+?)\s+"
    r"(?P<op>[\w\-]+)\(")
_PEAK_USE_RE = re.compile(r"%([\w.\-]+)")


def _top_level_tuple_bytes(shape_text):
    """Byte size of each top-level element of a (possibly tuple) shape."""
    s = shape_text.strip()
    if not s.startswith("("):
        return [_shape_bytes(s)]
    parts, depth, start = [], 0, 1
    for i, ch in enumerate(s):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
            if depth == 0:
                parts.append(s[start:i])
                break
        elif ch == "," and depth == 1:
            parts.append(s[start:i])
            start = i + 1
    return [_shape_bytes(p) for p in parts]


def estimate_peak_memory(hlo_text):
    """Static peak device memory of a scheduled HLO module, by buffer
    liveness.

    jax's ``compile().as_text()`` dumps the *scheduled* module
    (``is_scheduled=true``), so line order within a computation is
    execution order. Each op line defines a buffer of its output shape's
    size, alive from its definition to its last textual use; views and
    bookkeeping (``parameter``/``get-tuple-element``/``tuple``/
    ``bitcast``/async ``-done``) define nothing. Called computations
    contribute their own internal peak at the call line — a ``while``
    body's footprint lands on the ``while`` op (a loop's peak does not
    scale with its trip count, unlike its collective *volume*, which is
    why the two walks are separate), ``conditional`` branches contribute
    the max across branches (one executes), and ``fusion`` bodies
    contribute nothing (fused ops never materialize). Donation-aware:
    root outputs aliased to entry parameters via ``input_output_alias``
    reuse the argument's buffer and allocate nothing new.

    Returns a dict::

        peak_bytes            argument + liveness peak (per device —
                              SPMD entry shapes are already local)
        temp_peak_bytes       liveness peak alone (intermediates +
                              un-aliased outputs)
        parameter_bytes       entry argument footprint
        output_bytes          entry root footprint
        donated_output_bytes  root bytes aliased onto donated arguments
        per_computation       {computation: internal peak}

    Against XLA's own buffer assignment (``compiled.memory_analysis()``)
    this is an *upper bound*: buffer assignment additionally reuses
    dead buffers' allocations for same-sized successors, which pure
    liveness does not model. The bench row reports both sides.
    """
    comps, entry = split_computations(hlo_text)
    if not comps or entry is None:
        comps = {"<flat>": [l for l in hlo_text.splitlines() if l.strip()]}
        entry = "<flat>"
        aliases = []
    else:
        aliases = input_output_aliases(hlo_text)

    peak_memo = {}

    def callee_contribution(op, line):
        if op == "fusion":
            return 0
        subs = []
        if op == "while":
            for rx in (_BODY_REF_RE, _COND_REF_RE):
                m = rx.search(line)
                if m and m.group(1) in comps:
                    subs.append(peak_of(m.group(1)))
            return max(subs, default=0)
        if op == "conditional":
            m = _BRANCHES_RE.search(line)
            if m:
                for ref in m.group(1).split(","):
                    ref = ref.strip().lstrip("%")
                    if ref in comps:
                        subs.append(peak_of(ref))
            for rx in (_TRUE_REF_RE, _FALSE_REF_RE):
                m = rx.search(line)
                if m and m.group(1) in comps:
                    subs.append(peak_of(m.group(1)))
            return max(subs, default=0)
        for rx in (_CALLS_REF_RE, _TO_APPLY_RE):
            m = rx.search(line)
            if m and m.group(1) in comps:
                subs.append(peak_of(m.group(1)))
        return max(subs, default=0)

    def line_alloc(op, shape):
        if op in _ZERO_COST_OPS or op.endswith("-done"):
            return 0
        if op == "while":
            return 0   # the carry aliases the while's operand buffer
        if op.endswith("-start") and shape.strip().startswith("("):
            # async tuple = (operands..., results..., scratch scalars):
            # operands alias existing buffers; only results are new.
            elems = _element_bytes(shape, skip_scalars=True)
            return sum(b for _, b in elems[len(elems) // 2:])
        return _shape_bytes(shape)

    def walk(name, donated_root=0, donated_defs=()):
        """(liveness peak, parameter bytes, root bytes) of one
        computation. ``donated_defs``: def names whose buffers are
        written in place into donated arguments (allocate nothing)."""
        lines = comps[name]
        parsed = []        # (def name, alloc, callee peak)
        param_bytes = 0
        root_bytes = 0
        for line in lines:
            m = _PEAK_DEF_RE.match(line)
            if m is None:
                parsed.append(None)
                continue
            op = m.group("op")
            shape = m.group("shape")
            is_root = line.lstrip().startswith("ROOT")
            alloc = line_alloc(op, shape)
            if op == "parameter":
                param_bytes += _shape_bytes(shape)
            if m.group("name") in donated_defs:
                alloc = 0
            if is_root:
                root_bytes = _shape_bytes(shape)
                alloc = max(0, alloc - donated_root)
            parsed.append((m.group("name"), alloc,
                           callee_contribution(op, line)))
        defined = {p[0]: i for i, p in enumerate(parsed)
                   if p is not None}
        last_use = dict(defined)
        for i, line in enumerate(lines):
            for use in _PEAK_USE_RE.findall(line):
                if use in last_use and i > last_use[use]:
                    last_use[use] = i
        free_at = {}
        for dname, i in defined.items():
            free_at.setdefault(last_use[dname], []).append(
                parsed[i][1])
        live = peak = 0
        for i, p in enumerate(parsed):
            if p is None:
                continue
            live += p[1]
            peak = max(peak, live + p[2])
            for b in free_at.get(i, ()):
                live -= b
        return peak, param_bytes, root_bytes

    def peak_of(name):
        if name not in peak_memo:
            peak_memo[name] = 0       # cycle guard
            peak_memo[name] = walk(name)[0]
        return peak_memo[name]

    # Donated output bytes: per-aliased-entry sizes of the root tuple.
    # When ROOT is a `tuple` view the aliased buffers are the tuple's
    # operand defs — written in place into the donated argument, so
    # those defs allocate nothing; otherwise subtract off the root def.
    root_shape = None
    root_op = None
    root_operands = []
    for line in comps[entry]:
        if line.lstrip().startswith("ROOT"):
            m = _PEAK_DEF_RE.match(line)
            if m:
                root_shape = m.group("shape")
                root_op = m.group("op")
                root_operands = _PEAK_USE_RE.findall(
                    line[m.end():])
    donated = 0
    donated_defs = set()
    if root_shape is not None and aliases:
        elems = _top_level_tuple_bytes(root_shape)
        for e in aliases:
            oi = e["output_index"]
            if not oi:
                donated += sum(elems)
            elif oi[0] < len(elems):
                donated += elems[oi[0]]
                if root_op == "tuple" and oi[0] < len(root_operands):
                    donated_defs.add(root_operands[oi[0]])
    entry_peak, param_bytes, root_bytes = walk(
        entry, donated_root=0 if root_op == "tuple" else donated,
        donated_defs=donated_defs)
    per_comp = {entry: entry_peak}
    per_comp.update(peak_memo)
    return {
        "peak_bytes": param_bytes + entry_peak,
        "temp_peak_bytes": entry_peak,
        "parameter_bytes": param_bytes,
        "output_bytes": root_bytes,
        "donated_output_bytes": donated,
        "per_computation": per_comp,
    }


# ---------------------------------------------------------------------------
# input/output aliasing (donation) and host transfers
# ---------------------------------------------------------------------------

_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{[\d,\s]*\}(?:,\s*([\w-]+))?\)")


def input_output_aliases(hlo_text):
    """Parse the module header's ``input_output_alias`` map.

    Returns ``[{output_index: tuple, param_number: int, kind: str}]`` —
    the executable's actual buffer donations, to diff against what the
    engine *declared* via ``donate_argnums``.
    """
    key = "input_output_alias="
    i = hlo_text.find(key)
    if i < 0:
        return []
    s = hlo_text[i + len(key):]
    depth = 0
    end = 0
    for j, ch in enumerate(s):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                end = j
                break
    inner = s[1:end]
    return [
        {"output_index": tuple(int(x) for x in oi.split(",") if x.strip()),
         "param_number": int(pn),
         "kind": kind or "may-alias"}
        for oi, pn, kind in _ALIAS_ENTRY_RE.findall(inner)
    ]


def aliased_param_numbers(hlo_text):
    """Entry-parameter numbers the executable aliases into its outputs."""
    return {e["param_number"] for e in input_output_aliases(hlo_text)}


def _dims_superset(dims, want):
    """True iff multiset ``dims`` contains multiset ``want``."""
    from collections import Counter
    have = Counter(dims)
    return all(have[d] >= n for d, n in Counter(want).items())


def payload_shaped_dots(hlo_text, payload_dims):
    """Dot ops touching a cache-payload-shaped array.

    A dot line counts when any shape on it (output or operand) has a
    dim MULTISET containing ``payload_dims`` — for the decode program
    that is exactly a dense attention contraction over the full
    ``[max_batch, max_seq, n_head, head_dim]`` KV buffer (the einsum's
    batched layout permutes those dims, hence multiset, and no other
    decode dot carries all four sizes at once). The flash-decode audit
    pins this list empty: the Pallas kernel's dots only ever see
    ``block_k``-sized cache slices.
    """
    out = []
    want = tuple(int(d) for d in payload_dims)
    for line in hlo_text.splitlines():
        if " dot(" not in line:
            continue
        for _, dims in _SHAPE_RE.findall(line):
            if not dims:
                continue
            ds = [int(x) for x in dims.split(",")]
            if len(ds) >= len(want) and _dims_superset(ds, want):
                out.append(line.strip())
                break
    return out


def seq_sized_value_bytes(hlo_text, seq):
    """Total bytes of value DEFINITIONS carrying a ``seq``-sized dim,
    entry parameters excluded — a compile-time proxy for how much
    cache-length-proportional data a decode step materializes. The
    dense path defines attention-score rows, softmax temporaries and
    (quantized) dequant copies all shaped ``[..., max_seq, ...]``; the
    flash kernel's working set is ``block_k``-sized, so only the
    written-back cache itself survives at this size. Parameters are
    excluded because both paths take the identical cache buffers as
    inputs — the A/B signal is in what the program CREATES.
    """
    total = 0
    seq = int(seq)
    for line in hlo_text.splitlines():
        ls = line.strip()
        if "parameter(" in ls or "=" not in ls:
            continue
        shape_text = ls.split("=", 1)[1]
        # shape(s) sit between '=' and the op name; stop at the first
        # opcode paren to avoid re-counting operand shapes.
        op_at = shape_text.find("(")
        if op_at >= 0:
            shape_text = shape_text[:op_at]
        for dtype, dims in _SHAPE_RE.findall(shape_text):
            if dtype not in _DTYPE_BYTES or not dims:
                continue
            ds = [int(x) for x in dims.split(",")]
            if seq not in ds:
                continue
            n = 1
            for d in ds:
                n *= d
            total += n * _DTYPE_BYTES[dtype]
    return total


def payload_shaped_values(hlo_text, dtype, payload_dims):
    """Count value DEFINITIONS of ``dtype`` whose dims contain
    ``payload_dims`` (multiset). With a quantized KV cache these are
    full-precision cache-sized intermediates — the dense path's
    dequantized copies; the per-head scale planes lack ``head_dim`` so
    they never match. Zero under flash decode: dequantization happens
    in-register on ``block_k`` slices."""
    n = 0
    want = tuple(int(d) for d in payload_dims)
    defre = re.compile(r"=\s+" + re.escape(dtype) + r"\[([\d,]+)\]")
    for line in hlo_text.splitlines():
        m = defre.search(line)
        if not m:
            continue
        ds = [int(x) for x in m.group(1).split(",")]
        if len(ds) >= len(want) and _dims_superset(ds, want):
            n += 1
    return n


# Custom-call targets that round-trip through the Python host (jax
# pure_callback / io_callback / debug.callback lower to these).
_HOST_CALLBACK_TARGETS = (
    "xla_python_cpu_callback",
    "xla_python_gpu_callback",
    "xla_ffi_python_cpu_callback",
    "xla_ffi_python_gpu_callback",
    "xla_ffi_partitioned_python_cpu_callback",
)
_INOUTFEED_RE = re.compile(r"=\s+.+?\s+(infeed|outfeed)(-done)?\(")


def host_transfer_ops(hlo_text):
    """Ops that move data between device and host inside the program:
    ``[{kind, line}]`` with kind in {"infeed", "outfeed",
    "host-transfer", "host-callback"}. A compiled train step should have
    none — each one forces a device/host sync in the middle of the step.
    """
    hits = []
    for line in hlo_text.splitlines():
        ls = line.strip()
        m = _INOUTFEED_RE.search(ls)
        if m and not m.group(2):
            hits.append({"kind": m.group(1), "line": ls})
            continue
        if "is_host_transfer=true" in ls:
            hits.append({"kind": "host-transfer", "line": ls})
            continue
        if "custom-call" in ls:
            for target in _HOST_CALLBACK_TARGETS:
                if f'custom_call_target="{target}"' in ls:
                    hits.append({"kind": "host-callback", "line": ls})
                    break
    return hits
