"""deepspeed_tpu: a TPU-native training framework with the capabilities of
DeepSpeed (v0.3.2) — ZeRO, pipeline parallelism, mixed precision, fused ops —
re-designed for JAX/XLA/Pallas over named device meshes.

Public surface mirrors the reference `deepspeed/__init__.py`:
``initialize()`` (:47), ``add_config_arguments()`` (:190), plus the engine,
pipeline, ops and checkpointing exports.
"""

from deepspeed_tpu.version import version as __version__, git_hash, git_branch
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments
from deepspeed_tpu.runtime.activation_checkpointing import (
    checkpointing)
from deepspeed_tpu.utils.logging import logger, log_dist


def _parse_version(version_str):
    parts = version_str.split(".")
    return int(parts[0]), int(parts[1]), parts[2] if len(parts) > 2 else "0"


__version_major__, __version_minor__, __version_patch__ = \
    _parse_version(__version__)


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               loss_fn=None,
               params=None,
               param_specs=None,
               mesh=None,
               seed=0):
    """Initialize the engine — analog of ``deepspeed.initialize``
    (`deepspeed/__init__.py:47`).

    Model contract (TPU-native): a pure ``loss_fn(params, batch, rng)`` plus
    an initial ``params`` pytree (or a model object exposing ``.loss_fn`` /
    ``.params``); a :class:`deepspeed_tpu.pipe.PipelineModule` routes to the
    pipeline engine, mirroring the reference's engine dispatch
    (`deepspeed/__init__.py:106-128`).

    Returns the tuple ``(engine, optimizer, training_dataloader,
    lr_scheduler)`` for drop-in familiarity.
    """
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    log_dist(f"deepspeed_tpu info: version={__version__}, "
             f"git-hash={git_hash}, git-branch={git_branch}", ranks=[0])

    if isinstance(model, PipelineModule):
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
        engine = PipelineEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mpu=mpu,
                                dist_init_required=dist_init_required,
                                collate_fn=collate_fn,
                                config=config,
                                config_params=config_params,
                                mesh=mesh,
                                seed=seed)
    else:
        engine = DeepSpeedEngine(args=args,
                                 model=model,
                                 optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler,
                                 mpu=mpu,
                                 dist_init_required=dist_init_required,
                                 collate_fn=collate_fn,
                                 config=config,
                                 config_params=config_params,
                                 loss_fn=loss_fn,
                                 params=params,
                                 param_specs=param_specs,
                                 mesh=mesh,
                                 seed=seed)

    return_items = [
        engine,
        getattr(engine, "client_optimizer", None),
        engine.training_dataloader,
        getattr(engine, "lr_scheduler", None),
    ]
    return tuple(return_items)


def add_config_arguments(parser):
    """Add ``--deepspeed``/``--deepspeed_config`` CLI flags
    (reference `deepspeed/__init__.py:139-187`)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed",
                       default=False,
                       action="store_true",
                       help="Enable DeepSpeed (helper flag for user code, no "
                            "impact on engine behavior)")
    group.add_argument("--deepspeed_config",
                       default=None,
                       type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepspeed_mpi",
                       default=False,
                       action="store_true",
                       help="Run via MPI; rank/world size discovered from the "
                            "MPI environment.")
    return parser
