"""Pipeline-parallelism public surface (reference `deepspeed/pipe/__init__.py`)."""

from deepspeed_tpu.runtime.pipe.module import (
    LayerSpec,
    PipelineModule,
    TiedLayerSpec,
)

__all__ = ["PipelineModule", "LayerSpec", "TiedLayerSpec"]
